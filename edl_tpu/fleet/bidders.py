"""Bidders: how jobs present themselves to the chip market.

The Pathways posture (PAPERS.md): one coordinator substrate, and every
workload — N elastic trainers, M serving fleets — is just a *bidder*
against one TPU inventory.  Each tick a bidder distills its live state
into a ``Bid``:

- **Training** bids carry a *priority* and a *utility* — observed
  goodput-per-chip from the PR 7 ledger, read back through the job
  coordinator's merged telemetry.  Utility is the market's objective;
  priority orders preemption (lowest tier is preempted first) and
  growth tiers.
- **Serving** bids carry a *hard requirement*: the replica count the
  SLO band demands right now (p95-over-window-delta / queue depth /
  rejections — exactly the ``ServingLane`` signals, reused via
  ``ServingLane.desired_replicas``).  The arbiter satisfies
  requirements before any training growth, preempting trainers when
  the free pool is short.

Bidders also own their *actuation transport* (the job's coordinator
client): the arbiter decides, then each bidder actuates its own
transition with the standard prewarm→retarget handshake under the
decision's minted trace id, and training scale-downs wait for the
consensus victim-drain ack before their chips are considered free.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from edl_tpu.autoscaler.scaler import wait_for_world_ack


@dataclass
class Bid:
    """One bidder's stake in this tick's market, in whole *units*
    (trainer replicas / serving replicas), each worth
    ``chips_per_unit`` chips."""

    name: str
    kind: str  # "training" | "serving"
    priority: int
    chips_per_unit: int
    min_units: int
    max_units: int
    current_units: int
    #: ascending legal unit counts within [min, max]; empty = every
    #: integer (slice/batch quantization, same contract as
    #: ``JobView.legal_sizes``)
    legal_units: List[int] = field(default_factory=list)
    #: serving hard constraint: units the SLO band demands NOW (the
    #: arbiter treats it as a floor it preempts for); None for training
    required_units: Optional[int] = None
    #: training objective: observed goodput-per-chip (None = not yet
    #: observable — falls back behind every measured bid in its tier)
    utility: Optional[float] = None
    #: raw observation inputs (journaled into the decision entry)
    observed: Dict[str, object] = field(default_factory=dict)
    elastic: bool = True

    # -- legal-size stepping (mirrors JobView's) -----------------------------
    def _sizes(self) -> List[int]:
        if self.legal_units:
            return [
                u
                for u in self.legal_units
                if self.min_units <= u <= self.max_units
            ]
        return list(range(self.min_units, self.max_units + 1))

    def next_up(self, units: int) -> Optional[int]:
        for s in self._sizes():
            if s > units:
                return s
        return None

    def next_down(self, units: int) -> Optional[int]:
        for s in reversed(self._sizes()):
            if s < units:
                return s
        return None

    def clamp(self, units: int) -> int:
        sizes = self._sizes()
        if not sizes:
            return units
        best = sizes[0]
        for s in sizes:
            if s <= units:
                best = s
        return best

    def fulfillment(self) -> float:
        if self.min_units >= self.max_units:
            return 1.0
        return (self.current_units - self.min_units) / (
            self.max_units - self.min_units
        )


class TrainingBidder:
    """One elastic training job as a market participant.

    ``coordinator``: the JOB's coordinator client (Local or HTTP —
    ``metrics``/``telemetry``/``set_prewarm``/``set_target_world``).
    Utility = goodput_frac / allocated chips: a job already holding
    many chips needs a proportionally better ledger to out-bid a
    starved one — the diminishing-returns shape that makes the market
    spread chips instead of feeding one job forever."""

    def __init__(
        self,
        name: str,
        coordinator,
        *,
        priority: int = 0,
        chips_per_unit: int = 1,
        min_units: int = 1,
        max_units: int = 1,
        legal_units: Optional[List[int]] = None,
        observe: Optional[Callable[[], dict]] = None,
    ):
        if min_units < 1 or max_units < min_units:
            raise ValueError(
                f"bad unit bounds [{min_units}, {max_units}] for {name}"
            )
        self.name = name
        self.kind = "training"
        self.coordinator = coordinator
        self.priority = priority
        self.chips_per_unit = max(1, chips_per_unit)
        self.min_units = min_units
        self.max_units = max_units
        self.legal_units = sorted(set(legal_units)) if legal_units else []
        self._observe = observe

    @staticmethod
    def from_job(job, coordinator) -> "TrainingBidder":
        """Bidder from a validated ``TrainingJob`` spec: priority,
        [min, max], slice chips, and batch-quantized legal sizes all
        come from the resource model."""
        t = job.spec.trainer
        return TrainingBidder(
            job.name,
            coordinator,
            priority=job.spec.priority,
            chips_per_unit=max(1, job.tpu_per_trainer()),
            min_units=t.min_instance,
            max_units=t.max_instance,
            legal_units=job.legal_world_sizes(),
        )

    def _observation(self) -> dict:
        if self._observe is not None:
            return self._observe() or {}
        try:
            tel = self.coordinator.telemetry() or {}
        except Exception:
            return {}
        goodput = tel.get("goodput") or {}
        return {
            "goodput_frac": goodput.get("frac"),
            "step_rate": tel.get("step_rate"),
            "resize_cost_seconds": tel.get("resize_cost_seconds"),
        }

    def collect(self) -> Optional[Bid]:
        """One observation -> Bid; None when the coordinator is
        unreachable (an unobservable job must keep its holding — the
        market never reallocates what it cannot see)."""
        try:
            snap = self.coordinator.metrics() or {}
        except Exception:
            return None
        current = int(
            snap.get("target_world") or snap.get("world_size") or 0
        ) or self.min_units
        obs = self._observation()
        frac = obs.get("goodput_frac")
        utility = None
        if frac is not None:
            chips = max(1, current * self.chips_per_unit)
            utility = float(frac) / chips
        return Bid(
            name=self.name,
            kind=self.kind,
            priority=self.priority,
            chips_per_unit=self.chips_per_unit,
            min_units=self.min_units,
            max_units=self.max_units,
            current_units=current,
            legal_units=list(self.legal_units),
            utility=utility,
            observed=obs,
            elastic=self.min_units < self.max_units,
        )

    # -- actuation ----------------------------------------------------------
    def actuate(self, units: int, trace_id: str) -> bool:
        """Prewarm-then-retarget under the decision's trace id (the
        same zero-stall handshake as the single-job lanes)."""
        try:
            hint = getattr(self.coordinator, "set_prewarm", None)
            if hint is not None:
                hint(units, trace_id=trace_id)
        except Exception:
            pass  # advisory; the retarget still scales
        try:
            self.coordinator.set_target_world(units, trace_id=trace_id)
            return True
        except Exception:
            return False

    def wait_drain(self, timeout: float) -> bool:
        """Consensus-clean scale-down: block until every member of the
        retargeted world acked the new generation (victims left at the
        data-plane-agreed stop boundary).  The arbiter calls this
        before treating a preempted trainer's chips as free."""
        return wait_for_world_ack(self.coordinator, timeout)


class ServingBidder:
    """One serving fleet as a market participant: the ``ServingLane``'s
    SLO band becomes a HARD requirement the arbiter must cover.

    ``lane``: an ``autoscaler.serving.ServingLane`` — supplies the
    observation (p95-over-window-delta / queue / rejections), the
    band decision with its hysteresis (``desired_replicas``), the
    replica bounds, and the serving coordinator used for actuation.
    Do NOT also ``attach_serving_lane`` the same lane: in market mode
    the arbiter owns actuation (a lane attached to the plain
    autoscaler tick would race it).

    ``signals``: optional override returning the observation dict
    (scripted storms in tests/bench)."""

    def __init__(
        self,
        name: str,
        lane,
        *,
        priority: int = 0,
        chips_per_unit: int = 1,
        signals: Optional[Callable[[], dict]] = None,
    ):
        self.name = name
        self.kind = "serving"
        self.lane = lane
        self.priority = priority
        self.chips_per_unit = max(1, chips_per_unit)
        self.min_units = lane.min_replicas
        self.max_units = lane.max_replicas
        self._signals = signals

    @property
    def coordinator(self):
        return self.lane.coordinator

    def collect(self) -> Optional[Bid]:
        try:
            obs = (
                self._signals() if self._signals is not None
                else self.lane.observe()
            ) or {}
            current = self.lane.current_replicas()
        except Exception:
            return None
        required, reason = self.lane.desired_replicas(obs, current)
        obs = dict(obs)
        obs["slo_reason"] = reason
        return Bid(
            name=self.name,
            kind=self.kind,
            priority=self.priority,
            chips_per_unit=self.chips_per_unit,
            min_units=self.min_units,
            max_units=self.max_units,
            current_units=current,
            required_units=required,
            observed=obs,
            elastic=self.min_units < self.max_units,
        )

    def actuate(self, units: int, trace_id: str) -> bool:
        try:
            before = self.lane.current_replicas()
        except Exception:
            before = self.min_units
        if units < before:
            # Drain-victim-ack-then-patch (ISSUE 15): the market's
            # serving scale-downs follow the SAME contract as the
            # lane's — and ride the lane's live KV migration (ISSUE
            # 16): drain_victims picks a surviving replica and each
            # victim hands its in-flight generations over instead of
            # waiting them out, so a market preemption acks in O(KV
            # transfer), not O(longest generation).  No ack -> no
            # actuation this tick; the arbiter's fixed point
            # re-proposes next tick and the already-started drain is
            # usually finished by then.
            try:
                drain = self.lane.drain_victims(before, units)
            except Exception:
                # fail CLOSED: a broken drain handshake blocks the
                # actuation (the arbiter re-proposes next tick) —
                # never "drain skipped, delete anyway"
                drain = {"acked": False}
            if not drain.get("acked", True):
                return False
        try:
            self.coordinator.set_prewarm(units, trace_id=trace_id)
        except Exception:
            pass  # advisory
        try:
            self.coordinator.set_target_world(units, trace_id=trace_id)
        except Exception:
            return False
        if self.lane.on_scale is not None:
            try:
                self.lane.on_scale(before, units)
            except Exception:
                pass  # kube glue is best-effort; the retarget stands
        return True

    def wait_drain(self, timeout: float) -> bool:
        """Serving scale-downs drain their victims inside ``actuate``
        (drain-ack-then-patch), so by the time the arbiter asks, the
        chips are genuinely free — no extra wait."""
        return True
