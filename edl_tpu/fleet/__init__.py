"""edl_tpu.fleet — the multi-job cluster arbiter.

N elastic trainers + M serving fleets bidding for ONE TPU inventory:
the reference autoscaler's cluster-wide dry-run fixed point
(``pkg/autoscaler.go:296-337``) generalized with per-job priorities,
serving SLOs as hard constraints, observed goodput-per-chip as the
objective, and consensus-clean preemption of the lowest-priority
trainer to absorb serving spikes (chips return when the spike clears).

- ``inventory.ChipInventory`` — the market's chip ledger
- ``bidders.TrainingBidder`` / ``bidders.ServingBidder`` — per-job
  observation + actuation adapters (``Bid`` is the tick's message)
- ``arbiter.arbitrate`` — the pure fixed point;
  ``arbiter.FleetArbiter`` — the tick driver;
  ``arbiter.attach_fleet`` — ride the training autoscaler's 5s tick
"""

from edl_tpu.fleet.arbiter import (  # noqa: F401
    Arbitration,
    FleetArbiter,
    arbitrate,
    attach_fleet,
)
from edl_tpu.fleet.bidders import (  # noqa: F401
    Bid,
    ServingBidder,
    TrainingBidder,
)
from edl_tpu.fleet.inventory import ChipInventory  # noqa: F401
