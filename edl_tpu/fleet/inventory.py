"""One TPU chip inventory, N holders — the market's ledger.

The reference's dry run simulated a whole ``ClusterResource`` because
GPU pods also fought over CPU/memory.  The fleet market deliberately
reduces to the one axis every bidder actually contends on — TPU chips
— because serving replicas and trainer slices on a TPU cluster are
chip-bounded (their CPU/memory requests ride along with the slice) and
the per-axis machinery already lives in ``autoscaler/algorithm.py`` for
the intra-job fixed point.  Keeping the arbiter's ledger scalar keeps
the cross-job fixed point provably convergent (see
``arbiter.arbitrate``).

``ChipInventory`` is a plain mutable value type like
``ClusterResource``: the arbiter mutates a copy per dry run and tests
fabricate inventories as literals.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from edl_tpu.cluster.resources import ClusterResource


@dataclass
class ChipInventory:
    """Chip totals plus per-holder allocations (name -> chips).

    ``holdings`` tracks what the market has ALLOCATED, which on a live
    cluster equals scheduled pods' chip limits; chips outside any
    holding (e.g. non-fleet workloads) are modeled by seeding a
    holding the arbiter never owns."""

    total_chips: int = 0
    holdings: Dict[str, int] = field(default_factory=dict)

    def allocated(self) -> int:
        return sum(self.holdings.values())

    def free(self) -> int:
        return self.total_chips - self.allocated()

    def set_holding(self, name: str, chips: int) -> None:
        if chips < 0:
            raise ValueError(f"holding must be >= 0: {name}={chips}")
        if chips == 0:
            self.holdings.pop(name, None)
        else:
            self.holdings[name] = chips

    def snapshot(self) -> dict:
        """JSON-safe view (the ``edl fleet`` table + bench chips-over-
        time series read this shape)."""
        return {
            "total_chips": self.total_chips,
            "free_chips": self.free(),
            "holdings": dict(sorted(self.holdings.items())),
        }

    @staticmethod
    def from_cluster_resource(r: ClusterResource) -> "ChipInventory":
        """Seed the ledger from a live inventory inquiry: everything
        already scheduled outside the fleet's bidders is parked under
        one opaque holding so the market can never hand it out."""
        inv = ChipInventory(total_chips=r.tpu_total)
        used = r.tpu_total - r.free_chips()
        if used > 0:
            inv.set_holding("(scheduled)", used)
        return inv
