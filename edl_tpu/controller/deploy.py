"""Control-plane install manifests: everything ``kubectl apply`` needs
to run the controller in-cluster.

The reference shipped only a binary image (``/root/reference/
Dockerfile:1-9``) and registered its CRD at process start
(``cmd/edl/edl.go:39``); granting the controller permission to watch
TrainingJobs and rewrite Job parallelism was left to the operator.
Here the full set is rendered: CRD, namespace, ServiceAccount, the
least-privilege ClusterRole the control loops actually use (watch CRs;
CRUD trainer Jobs + coordinator Deployments/Services; read nodes/pods
for inventory), its binding, and the controller Deployment itself.
"""

from __future__ import annotations

from typing import Any, Dict, List

from edl_tpu.resource.training_job import DEFAULT_IMAGE, crd_manifest

NAMESPACE = "edl-system"
SERVICE_ACCOUNT = "edl-controller"


def rbac_manifests() -> List[Dict[str, Any]]:
    """ServiceAccount + ClusterRole + binding for the controller.

    The rules mirror the controller's real API surface (one verb set
    per call site): the CR watch (``watch.py``), workload CRUD
    (``kube.KubectlAPI``), and the inventory's node/pod lists
    (``cluster.inquiry_resource`` — ref ``pkg/cluster.go:176-242``)."""
    return [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": SERVICE_ACCOUNT, "namespace": NAMESPACE},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": SERVICE_ACCOUNT},
            "rules": [
                {
                    # Read-only on the CR objects themselves (the
                    # watcher polls; specs belong to users)...
                    "apiGroups": ["edl.tpu.dev"],
                    "resources": ["trainingjobs"],
                    "verbs": ["get", "list", "watch"],
                },
                {
                    # ...but the controller owns the status subresource
                    # (state machine writeback, SURVEY.md §5.5).
                    "apiGroups": ["edl.tpu.dev"],
                    "resources": ["trainingjobs/status"],
                    "verbs": ["update", "patch"],
                },
                {
                    "apiGroups": ["batch"],
                    "resources": ["jobs"],
                    "verbs": [
                        "get", "list", "watch",
                        "create", "update", "patch", "delete",
                    ],
                },
                {
                    "apiGroups": ["apps"],
                    "resources": ["deployments"],
                    "verbs": [
                        "get", "list", "watch",
                        "create", "update", "patch", "delete",
                    ],
                },
                {
                    # patch included: re-applying a rendered Service on
                    # ensure/refresh PATCHes the existing object.
                    "apiGroups": [""],
                    "resources": ["services"],
                    "verbs": [
                        "get", "list",
                        "create", "update", "patch", "delete",
                    ],
                },
                {
                    "apiGroups": [""],
                    "resources": ["nodes", "pods"],
                    "verbs": ["get", "list", "watch"],
                },
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": SERVICE_ACCOUNT},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": SERVICE_ACCOUNT,
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": SERVICE_ACCOUNT,
                    "namespace": NAMESPACE,
                }
            ],
        },
    ]


def controller_deployment(image: str = DEFAULT_IMAGE) -> Dict[str, Any]:
    """One controller replica (the decision plane is a singleton, like
    the reference binary — leader election is out of scope as it was
    there)."""
    labels = {"app": "edl-controller"}
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "edl-controller", "namespace": NAMESPACE},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "serviceAccountName": SERVICE_ACCOUNT,
                    "containers": [
                        {
                            "name": "controller",
                            "image": image,
                            "args": ["controller"],
                            "resources": {
                                "requests": {"cpu": "200m", "memory": "256Mi"}
                            },
                        }
                    ],
                },
            },
        },
    }


def deploy_manifests(image: str = DEFAULT_IMAGE) -> List[Dict[str, Any]]:
    """The full ``kubectl apply``-able control-plane install."""
    return [
        {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": NAMESPACE},
        },
        crd_manifest(),
        *rbac_manifests(),
        controller_deployment(image),
    ]
