"""Control-plane side of the actuation handshake.

The reference actuated by rewriting ``Job.Spec.Parallelism`` and
stopped there (``pkg/autoscaler.go:339-376``): pserver elasticity
needed no world agreement.  Our runtime does — the coordinator caps the
plan at its target world, so after (or before, on scale-down) the
parallelism PUT the control plane must also tell the job's coordinator
the new target (SURVEY.md §7.1 row 4: "Parallelism PUT *plus a
handshake*").  This module resolves a job's coordinator address and
builds the HTTP client the autoscaler/controller use for that POST.

Address resolution defaults to the coordinator Service's cluster DNS
name (``<job>-coordinator:<port>`` — what ``parse_to_coordinator``
renders).  ``EDL_COORD_ADDR_TEMPLATE`` overrides it for environments
without cluster DNS (tests, local runs): a format string with fields
``{name}`` (coordinator/service name), ``{namespace}``, ``{port}``,
``{job}``.
"""

from __future__ import annotations

import os

from edl_tpu.resource.training_job import TrainingJob

#: env override for the coordinator address template
ADDR_TEMPLATE_ENV = "EDL_COORD_ADDR_TEMPLATE"
#: Namespace-qualified Service DNS: the controller watches CRs
#: cluster-wide (``kubectl get -A``), so a bare ``{name}`` would
#: resolve against the controller pod's own namespace and the
#: handshake would silently never reach jobs elsewhere.
DEFAULT_ADDR_TEMPLATE = "{name}.{namespace}:{port}"


def coordinator_address(job: TrainingJob) -> str:
    template = os.environ.get(ADDR_TEMPLATE_ENV, DEFAULT_ADDR_TEMPLATE)
    return template.format(
        name=job.coordinator_name(),
        namespace=job.namespace,
        port=job.spec.port,
        job=job.name,
    )


def make_coord_client(
    job: TrainingJob,
    timeout: float = 2.0,
    retries: int = 1,
    retry_base_delay: float = 0.1,
    retry_deadline: float = None,
):
    """HTTP client for the job's coordinator.  Short timeout + a single
    try by default: the caller runs inside the 5s control loop and must
    tolerate a coordinator that is still scheduling (callers catch
    ``ConnectionError`` and retry on the next tick — the handshake is
    level-triggered, see ``Controller.reconcile_targets``).  When
    ``retries`` > 1 the backoff comes from ``utils.retry.RetryPolicy``
    (jittered, deadline-bounded) so a flapping coordinator can never
    hold a control tick hostage.  ``retry_deadline`` defaults to
    ``retries * (timeout + retry_base_delay)`` — sized so every
    requested attempt can actually run even when each one blocks its
    full connect timeout (a deadline at or below ``timeout`` would
    silently cap timeout-class failures at one attempt)."""
    from edl_tpu.runtime.coord_service import HTTPCoordinator

    if retry_deadline is None:
        retry_deadline = retries * (timeout + retry_base_delay)
    return HTTPCoordinator(
        coordinator_address(job),
        timeout=timeout,
        retries=retries,
        retry_base_delay=retry_base_delay,
        retry_deadline=retry_deadline,
    )
