"""TrainingJob watch source: the informer analog.

The reference watched the CRD through a client-go informer
(``pkg/controller.go:79-108``: ListWatch + NewInformer, resync 0) and
dispatched add/update/delete to the autoscaler.  Kubernetes watches are
just long-polled lists with resourceVersion bookmarks; a plain
poll-and-diff loop provides the same semantics with zero client
dependencies, and the list function is injected so tests, local-sim,
and a real cluster (``KubectlAPI.list_training_jobs``) all drive the
identical controller object.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List

from edl_tpu.resource.training_job import TrainingJob


class TrainingJobWatcher:
    def __init__(
        self,
        list_fn: Callable[[], List[dict]],
        controller,
    ):
        """``list_fn``: returns the current TrainingJob CR manifests
        (dicts).  ``controller``: an ``edl_tpu.controller.Controller``."""
        self._list = list_fn
        self.controller = controller
        self._seen: Dict[str, str] = {}  # name -> canonical spec json

    @staticmethod
    def _fingerprint(manifest: dict) -> str:
        return json.dumps(manifest.get("spec", {}), sort_keys=True)

    @staticmethod
    def _meta_fingerprint(manifest: dict) -> str:
        """Mutable metadata an informer would surface as an update:
        labels AND annotations (a real informer fires on any metadata
        change; resourceVersion-free polling approximates that with the
        two fields users actually edit)."""
        meta = manifest.get("metadata", {}) or {}
        return json.dumps(
            {
                "labels": meta.get("labels", {}),
                "annotations": meta.get("annotations", {}),
            },
            sort_keys=True,
        )

    def poll_once(self) -> int:
        """Diff the listed CRs against the known set; fire on_add /
        on_update / on_delete (ref handler set, ``:110-147``), then a
        **level-triggered** pass: GC workloads whose owning CR is gone.
        The edge-triggered diff alone loses deletions that happened
        while no controller was running (in-memory ``_seen`` state);
        the GC pass converges from observed state regardless of event
        history.  Returns the number of events dispatched."""
        current: Dict[str, dict] = {}
        for m in self._list():
            try:
                name = m["metadata"]["name"]
            except (KeyError, TypeError):
                continue
            current[name] = m

        events = 0
        for name, m in current.items():
            fp = self._fingerprint(m) + self._meta_fingerprint(m)
            if name not in self._seen:
                self.controller.on_add(TrainingJob.from_manifest(m))
                events += 1
            elif self._seen[name] != fp:
                self.controller.on_update(TrainingJob.from_manifest(m))
                events += 1
            self._seen[name] = fp
        for name in [n for n in self._seen if n not in current]:
            del self._seen[name]
            job = self.controller.jobs.get(name)
            if job is not None:
                self.controller.on_delete(job)
                events += 1
        self.controller.gc_orphans(current.keys())
        return events
