"""Job lifecycle: create/ensure/teardown of a job's cluster objects.

The reference's ``TrainingJober`` (``pkg/trainingjober.go``) did this
for trainer Job + pserver RS + master RS — but was **never wired in**
(SURVEY.md §1 "orphaned"; creation happened in the external paddlecloud
server).  Here the lifecycle is owned by the controller, as the
reference's own TODO intended (``pkg/controller.go:115-133``), and a
job is two objects: trainer workload + coordinator.

Semantics kept from the reference: ``ensure`` = bounded retries with a
pause (ref 3 tries x 1s, ``pkg/trainingjober.go:25-28,196-207``);
partial-creation rollback (ref ``:170-189``); ``complete`` tears down
the coordinator but leaves the trainer workload for GC (ref
``Complete`` kept the trainer Job, ``:126-132``); ``destroy`` removes
everything (ref ``:135-140``).
"""

from __future__ import annotations

import time
from typing import Callable

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.resource.training_job import TrainingJob

ENSURE_ATTEMPTS = 3  # ref convertedJobMaxRetryCount (pkg/trainingjober.go:25-28)
ENSURE_PAUSE_SECONDS = 1.0


class JobLifecycle:
    def __init__(
        self,
        cluster: Cluster,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.cluster = cluster
        self._sleep = sleep

    # -- create -------------------------------------------------------------
    def check_and_create(self, job: TrainingJob) -> bool:
        """Create whichever of the job's objects are missing — by
        applying the jobparser's rendered manifests — with rollback of
        this call's creations on failure (ref ``checkAndCreate``,
        ``pkg/trainingjober.go:142-193``)."""
        from edl_tpu.controller.jobparser import parse_to_coordinator

        created = []
        try:
            if self.cluster.kube.get_workload(job.coordinator_name()) is None:
                # record intent BEFORE applying: a mid-apply failure
                # must roll back the partial creation
                created.append(job.coordinator_name())
                self.cluster.kube.apply_manifests(parse_to_coordinator(job))
            if self.cluster.get_trainer_workload(job) is None:
                created.append(job.trainer_job_name())
                self.cluster.create_trainer_workload(job)
            return True
        except Exception:
            for name in created:  # rollback partial creation
                try:
                    if name == job.trainer_job_name():
                        # enumerates per-replica slice Jobs too — a bare
                        # delete_workload would orphan a multi-host
                        # job's partially created mh-trainer-N Jobs
                        self.cluster.delete_trainer_workload(job)
                    else:
                        self.cluster.kube.delete_workload(name)
                except Exception:
                    pass
            return False

    def ensure(self, job: TrainingJob) -> bool:
        """ref ``Ensure`` (``pkg/trainingjober.go:196-207``)."""
        for attempt in range(ENSURE_ATTEMPTS):
            if self.check_and_create(job):
                return True
            if attempt < ENSURE_ATTEMPTS - 1:
                self._sleep(ENSURE_PAUSE_SECONDS)
        return False

    # -- spec update --------------------------------------------------------
    def refresh(self, job: TrainingJob) -> bool:
        """Spec changed on a live job: re-render and re-apply its
        manifests so image/resource/env changes actually reach the
        running workload (the reference applied spec updates to the
        autoscaler's view only).  The actuated parallelism is preserved
        (clamped into the new [min, max]) so a spec edit doesn't stomp
        the autoscaler's plan."""
        from edl_tpu.controller.jobparser import (
            parse_to_coordinator,
            parse_to_trainer_manifests,
        )

        try:
            cur = self.cluster.get_trainer_workload(job)
            p = job.spec.trainer.min_instance
            if cur is not None:
                p = max(
                    job.spec.trainer.min_instance,
                    min(cur.parallelism, job.spec.trainer.max_instance),
                )
            if job.hosts_per_replica() > 1:
                # Re-apply the spec into the EXISTING replica Jobs the
                # clamp keeps (lowest indexes — the same ones
                # update_parallelism and the coordinator keep; rendering
                # range(p) instead would conjure fresh empty low-index
                # Jobs that then displace live high-index replicas).
                have = sorted(
                    int(w.name.rsplit("-", 1)[1])
                    for w in self.cluster._slice_jobs(job)
                )
                self.cluster.kube.apply_manifests(
                    parse_to_trainer_manifests(
                        job, replicas=p, indexes=have[:p] or None
                    )
                )
                # count convergence (creates missing / deletes excess)
                self.cluster.update_parallelism(job, p)
            else:
                self.cluster.kube.apply_manifests(
                    parse_to_trainer_manifests(job, replicas=p)
                )
            self.cluster.kube.apply_manifests(parse_to_coordinator(job))
            return True
        except Exception:
            import traceback

            traceback.print_exc()
            return False

    # -- teardown -----------------------------------------------------------
    def complete(self, job: TrainingJob) -> None:
        """Job finished: drop the coordinator, keep the trainer workload
        for inspection/GC (ref ``Complete``, ``:126-132``)."""
        self.cluster.kube.delete_workload(job.coordinator_name())

    def destroy(self, job: TrainingJob) -> None:
        """Job deleted: remove everything (ref ``Destroy``, ``:135-140``)."""
        self.cluster.kube.delete_workload(job.coordinator_name())
        self.cluster.delete_trainer_workload(job)
