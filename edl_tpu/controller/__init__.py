"""L2/L4: job parsing, lifecycle, controller, and the pod launcher."""

from edl_tpu.controller.jobparser import (
    JobParser,
    parse_to_trainer,
    parse_to_trainer_manifests,
    parse_to_trainer_slice,
    parse_to_coordinator,
    pod_env,
)
from edl_tpu.controller.lifecycle import JobLifecycle
from edl_tpu.controller.controller import Controller

__all__ = [
    "JobParser",
    "parse_to_trainer",
    "parse_to_trainer_manifests",
    "parse_to_trainer_slice",
    "parse_to_coordinator",
    "pod_env",
    "JobLifecycle",
    "Controller",
]
