"""L4 controller: the event plane + job status state machine.

The reference controller (``pkg/controller.go``) watches the
TrainingJob CRD through an informer and forwards add/update/delete to
the autoscaler (``:110-147``) — and that is *all*: creation was a
logged TODO (``:115-133``) and ``TrainingJobStatus`` was never written
(SURVEY.md §5.5).  This controller fixes both, as the reference's own
comments say it should:

- **wired creation/teardown** via ``JobLifecycle`` on add/delete,
- **a real status state machine** Created -> Running -> (Scaling) ->
  Succeed/Failed, driven from pod counts each reconcile, including the
  pending-time metric (a BASELINE.md north-star number).

The watch source is injected as a plain callback registry so local
mode, tests, and a real CRD informer (kubectl watch) all drive the same
object.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from edl_tpu.autoscaler.scaler import Autoscaler
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.controller.lifecycle import JobLifecycle
from edl_tpu.resource.training_job import JobState, TrainingJob


class Controller:
    def __init__(
        self,
        cluster: Cluster,
        autoscaler: Optional[Autoscaler] = None,
        lifecycle: Optional[JobLifecycle] = None,
        clock: Callable[[], float] = time.time,
        coord_client_factory=None,
    ):
        self.cluster = cluster
        self.autoscaler = autoscaler or Autoscaler(cluster)
        self.lifecycle = lifecycle or JobLifecycle(cluster)
        self.jobs: Dict[str, TrainingJob] = {}
        self._clock = clock
        self._stop = threading.Event()
        # One handshake transport for the whole control plane: default
        # to the autoscaler's factory so injecting it there (tests,
        # non-HTTP transports) covers the controller's half too.
        self._coord_client = (
            coord_client_factory or self.autoscaler._coord_client
        )
        #: spec updates whose manifest re-apply failed; retried per tick
        self._pending_refresh: set = set()
        #: last status payload pushed to each CR (avoid a PATCH per tick)
        self._pushed_status: Dict[str, str] = {}
        #: jobs whose coordinator handshake is currently failing (each
        #: outage logs once; cleared on recovery)
        self._handshake_down: set = set()
        #: jobs the fleet arbiter owns (ROADMAP item 2 residue): once
        #: >= 2 managed jobs carry ``spec.priority``, the controller
        #: constructs the chip-market arbiter itself and rides it on
        #: the autoscaler tick; these jobs leave the single-cluster
        #: lane (the market supersedes per-job planning for them)
        self._fleet_managed: set = set()

    # -- multi-job chip market (edl_tpu.fleet; ROADMAP item 2 residue) -------
    def _fleet_inventory(self):
        """Live chip ledger for the arbiter: everything scheduled
        OUTSIDE the fleet's own jobs parks under one opaque holding the
        market can never hand out (the fleet jobs' pods are the
        market's own allocations, not outside usage)."""
        from edl_tpu.fleet.inventory import ChipInventory

        r = self.cluster.inquiry_resource()
        inv = ChipInventory(total_chips=r.tpu_total)
        used = r.tpu_total - r.free_chips()
        fleet_used = 0
        workloads = self.cluster.trainer_workloads_map()
        for name in self._fleet_managed:
            job = self.jobs.get(name)
            w = workloads.get(name)
            if job is not None and w is not None:
                fleet_used += w.parallelism * job.tpu_per_trainer()
        outside = max(0, used - fleet_used)
        if outside:
            inv.set_holding("(scheduled)", outside)
        return inv

    def _maybe_attach_fleet(self) -> None:
        """Promote prioritized jobs into the chip market.  Once >= 2
        live jobs carry ``spec.priority`` (> 0), construct a
        ``FleetArbiter`` over the live inventory and ``attach_fleet``
        it to the autoscaler tick; jobs gaining a priority later join
        the market, jobs deleted or finished leave it.  An arbiter
        already attached (tests / custom markets via the explicit
        ``attach_fleet``) is left alone except for bidder sync of
        controller-managed jobs."""
        from edl_tpu.fleet import FleetArbiter, TrainingBidder, attach_fleet

        live = {
            name: job
            for name, job in self.jobs.items()
            if job.spec.priority > 0
            and job.status.state not in (JobState.SUCCEED, JobState.FAILED)
        }
        arbiter = getattr(self.autoscaler, "fleet_arbiter", None)
        if arbiter is None:
            if len(live) < 2:
                return
            arbiter = FleetArbiter(
                lambda: self._fleet_inventory(),
                trainers=[
                    TrainingBidder.from_job(job, self._coord_client(job))
                    for job in live.values()
                ],
            )
            attach_fleet(self.autoscaler, arbiter)
            self._fleet_managed = set(live)
            for job in live.values():
                # The market supersedes the single-cluster lane for
                # the jobs it owns — the scaler must not fight it.
                self.autoscaler.on_del(job)
            return
        # Bidder sync: controller-managed jobs only (explicitly
        # attached bidders for jobs this controller never saw stay).
        for name, job in live.items():
            if name in self._fleet_managed:
                # Spec edits to a market-owned job (priority raised,
                # bounds widened) must reach its bidder: on_update
                # keeps market jobs out of the single-cluster lane, so
                # the tick-time sync is where the arbiter learns.
                for b in arbiter.trainers:
                    if b.name == name:
                        fresh = TrainingBidder.from_job(job, b.coordinator)
                        b.priority = fresh.priority
                        b.chips_per_unit = fresh.chips_per_unit
                        b.min_units = fresh.min_units
                        b.max_units = fresh.max_units
                        b.legal_units = fresh.legal_units
                continue
            if not any(b.name == name for b in arbiter.trainers):
                arbiter.add_trainer(
                    TrainingBidder.from_job(job, self._coord_client(job))
                )
            # Claim the job even when an explicitly attached bidder
            # already carries its name: the job must still leave the
            # single-cluster lane, or the market and the per-job
            # planner issue conflicting retargets for one workload.
            self._fleet_managed.add(name)
            self.autoscaler.on_del(job)
        for name in self._fleet_managed - set(live):
            self._fleet_drop(name)
            job = self.jobs.get(name)
            if job is not None and job.status.state not in (
                JobState.SUCCEED,
                JobState.FAILED,
            ):
                # Still-live job that lost its priority: hand it back
                # to the single-cluster lane — owned by NEITHER
                # planner, it would never scale again.
                self.autoscaler.on_add(job)

    def _fleet_drop(self, name: str) -> None:
        """Remove a job's bidder from the market (deleted, terminal,
        or priority edited away)."""
        if name not in self._fleet_managed:
            return
        self._fleet_managed.discard(name)
        arbiter = getattr(self.autoscaler, "fleet_arbiter", None)
        if arbiter is not None:
            arbiter.trainers = [
                b for b in arbiter.trainers if b.name != name
            ]

    # -- event handlers (ref onAdd/onUpdate/onDelete, :110-147) --------------
    def on_add(self, job: TrainingJob) -> TrainingJob:
        """Validate, create cluster objects, hand to the autoscaler
        (the reference only logged here — its TODO, ``:115-133``)."""
        job = job.validate()
        job.status.state = JobState.CREATED
        job.status.submitted_at = self._clock()
        job.status.parallelism = job.spec.trainer.min_instance
        if not self.lifecycle.ensure(job):
            job.status.state = JobState.FAILED
            job.status.message = "failed to create trainer/coordinator objects"
            self.jobs[job.name] = job
            return job
        self.jobs[job.name] = job
        self.autoscaler.on_add(job)
        return job

    def on_update(self, job: TrainingJob) -> None:
        job = job.validate()
        old = self.jobs.get(job.name)
        if old is not None:
            job.status = old.status
        spec_changed = old is None or old.spec != job.spec
        self.jobs[job.name] = job
        if job.status.state in (JobState.SUCCEED, JobState.FAILED):
            # Terminal: a spec edit must not re-enroll the job in the
            # autoscaler or resurrect the coordinator that
            # mark_succeeded/complete already tore down.
            return
        if job.name not in self._fleet_managed:
            # Market-owned jobs stay OUT of the single-cluster lane: a
            # watch update re-enrolling one would have two planners
            # fighting over the same workload.  (A job whose priority
            # was edited away re-enters the lane via the market's
            # gone-sync, not here.)
            self.autoscaler.on_update(job)
        if spec_changed:
            # Re-render + re-apply so image/resource changes reach the
            # running workload (parallelism preserved; VERDICT r2 weak #9).
            # A failed apply queues for level-triggered retry each tick —
            # the next watch event carries the same spec, so the edge
            # alone would lose the update forever.
            if not self.lifecycle.refresh(job):
                self._pending_refresh.add(job.name)
            else:
                self._pending_refresh.discard(job.name)

    def on_delete(self, job: TrainingJob) -> None:
        self.autoscaler.on_del(job)
        self._fleet_drop(job.name)
        self.lifecycle.destroy(job)
        self.jobs.pop(job.name, None)
        # A resubmitted job with an identical status must hit the fresh
        # CR: drop the dedup key with the job.  Same for the handshake
        # outage marker — a new job's outage must log again.
        self._pushed_status.pop(job.name, None)
        self._pending_refresh.discard(job.name)
        self._handshake_down.discard(job.name)

    # -- status reconciliation (what the reference never did) ----------------
    def reconcile_status(
        self,
        pods_by_job: Optional[Dict] = None,
        workloads: Optional[Dict] = None,
    ) -> None:
        """Refresh every job's status from observed cluster state.
        ``pods_by_job`` / ``workloads``: share one pod-list and one
        workload-list snapshot across the tick's passes (each list is a
        kubectl subprocess on a real cluster; per-job gets would make
        the tick O(jobs))."""
        if pods_by_job is None:
            pods_by_job = self.cluster.job_pods_map()
        if workloads is None:
            workloads = self.cluster.trainer_workloads_map()
        for job in list(self.jobs.values()):
            if job.status.state in (JobState.SUCCEED, JobState.FAILED):
                continue
            w = workloads.get(job.name)
            if w is None:
                job.status.state = JobState.FAILED
                job.status.message = "trainer workload disappeared"
                self._freeze_pending_clock(job)
                continue
            total, running, pending, succeeded = pods_by_job.get(
                job.name, (0, 0, 0, 0)
            )
            job.status.parallelism = w.parallelism
            job.status.running = running
            job.status.pending = pending
            if total > 0 and succeeded == total:
                # Every trainer pod ran to completion (RestartPolicy
                # Never): the job is done — the terminal-pods completion
                # path (ref Complete, pkg/trainingjober.go:126-132).
                self.mark_succeeded(job.name)
                continue
            if job.status.state == JobState.CREATED and running > 0:
                job.status.state = JobState.RUNNING
                job.status.started_at = self._clock()
            elif job.status.state == JobState.RUNNING and pending > 0:
                job.status.state = JobState.SCALING
            elif job.status.state == JobState.SCALING and pending == 0:
                job.status.state = JobState.RUNNING
        self.push_statuses()

    def push_statuses(self) -> None:
        """Write each job's status to its CR's status subresource (only
        when it changed) so ``kubectl get trainingjobs`` reflects the
        controller's state machine — the reference declared
        ``TrainingJobStatus`` and never wrote it (SURVEY.md §5.5)."""
        import json

        for job in self.jobs.values():
            s = job.status
            payload = {
                "state": s.state.value,
                "parallelism": s.parallelism,
                "running": s.running,
                "pending": s.pending,
                "message": s.message,
            }
            key = json.dumps(payload, sort_keys=True)
            if self._pushed_status.get(job.name) == key:
                continue
            try:
                if self.cluster.kube.update_training_job_status(
                    job.name, payload, namespace=job.namespace
                ):
                    self._pushed_status[job.name] = key
            except Exception:
                continue  # next tick retries (level-triggered)

    # -- actuation handshake + completion (coordinator-facing) ---------------
    #: concurrent coordinator probes per tick: each probe can block on
    #: its connect timeout (~1-2s); serial probes would make the tick
    #: O(jobs x timeout)
    PROBE_WORKERS = 8

    def reconcile_targets(
        self,
        pods_by_job: Optional[Dict] = None,
        workloads: Optional[Dict] = None,
    ) -> None:
        """Level-triggered half of the actuation handshake: converge
        every live coordinator's target world onto the observed trainer
        parallelism, and fire completion when a coordinator reports the
        job finished.  The autoscaler POSTs targets eagerly at actuation
        time; this pass repairs any handshake that was lost (coordinator
        still scheduling, transient network) so the two halves cannot
        stay disconnected (VERDICT r2 #1).  Probes run with bounded
        concurrency, and a RUNNING job whose coordinator stays
        unreachable is logged (once per outage) — a bad Service or
        NetworkPolicy must not be invisible."""
        import sys
        from concurrent.futures import ThreadPoolExecutor

        if pods_by_job is None:
            pods_by_job = self.cluster.job_pods_map()
        if workloads is None:
            workloads = self.cluster.trainer_workloads_map()
        targets = []
        for job in list(self.jobs.values()):
            if job.status.state in (JobState.SUCCEED, JobState.FAILED):
                continue
            if pods_by_job.get(job.name, (0, 0, 0, 0))[1] == 0:
                # No trainer pod running yet: the coordinator is very
                # likely still scheduling too — don't burn the control
                # tick on connect timeouts (each probe can block ~1s).
                continue
            w = workloads.get(job.name)
            if w is None:
                continue
            targets.append((job, w.parallelism))
        if not targets:
            return

        def probe(item):
            job, parallelism = item
            try:
                # Factory contract is job -> client (scaler.py
                # docstring); keyword extras would break injected
                # factories.
                coord = self._coord_client(job)
                m = coord.metrics()
                if m.get("completed"):
                    return (job.name, "completed")
                if m.get("target_world") != parallelism:
                    coord.set_target_world(parallelism)
                return (job.name, "ok")
            except Exception as e:
                return (job.name, f"unreachable: {e}")

        with ThreadPoolExecutor(max_workers=self.PROBE_WORKERS) as pool:
            results = list(pool.map(probe, targets))
        for name, outcome in results:
            if outcome == "completed":
                self.mark_succeeded(name)
            elif outcome == "ok":
                self._handshake_down.discard(name)
            elif name not in self._handshake_down:
                # Log the outage once; clear on recovery so a later
                # outage logs again.  The handshake stays level-
                # triggered — the next tick retries regardless.
                self._handshake_down.add(name)
                print(
                    f"[edl-controller] coordinator handshake for {name} "
                    f"failing while the job has running trainers "
                    f"({outcome})",
                    file=sys.stderr,
                )

    # -- orphan GC (level-triggered, from observed state) --------------------
    def gc_orphans(self, live_cr_names) -> int:
        """Destroy framework-owned workloads whose TrainingJob CR no
        longer exists.  Kubernetes ownerReferences do this natively in a
        real cluster; this pass makes the controller itself converge
        from observed state — a controller restarted after ``edl kill``
        still cleans up (the reference's informers re-listed on start,
        ``pkg/controller.go:79-108``, but it never deleted anything).
        Returns the number of workloads deleted."""
        live = set(live_cr_names)
        deleted = 0
        for w in self.cluster.kube.list_workloads():
            if w.owner and w.owner not in live:
                if self.cluster.kube.delete_workload(w.name):
                    deleted += 1
        return deleted

    def mark_succeeded(self, name: str) -> None:
        """Terminal success (reported by the job's coordinator when the
        pass count completes).  The job leaves the autoscaler's managed
        set — a finished workload must never be rescaled back to life."""
        job = self.jobs.get(name)
        if job is not None:
            job.status.state = JobState.SUCCEED
            self._freeze_pending_clock(job)
            self.autoscaler.on_del(job)
            self.lifecycle.complete(job)
            self._handshake_down.discard(name)

    def _freeze_pending_clock(self, job: TrainingJob) -> None:
        """A job reaching a terminal state without ever being observed
        running must stop accruing pending time, or pending_p50_s would
        grow without bound while the terminal job lingers."""
        if job.status.started_at <= 0:
            job.status.started_at = self._clock()

    # -- run loop (ref Run, :64-76: watch goroutine + autoscaler goroutine) --
    def run_once(self) -> None:
        # One pod-list + one workload-list snapshot serve every pass
        # this tick: the tick costs O(1) kubectl subprocesses however
        # many jobs the controller manages.
        pods = self.cluster.kube.list_pods()
        pods_by_job = self.cluster.job_pods_map(pods)
        pod_nodes = self.cluster.job_pod_nodes_map(pods)
        workloads = self.cluster.trainer_workloads_map()
        self.reconcile_status(pods_by_job, workloads)
        # Chip market promotion/sync BEFORE the scaler tick: the
        # attached arbiter rides the same run_once below.
        self._maybe_attach_fleet()
        for name in list(self._pending_refresh):
            job = self.jobs.get(name)
            if job is None or self.lifecycle.refresh(job):
                self._pending_refresh.discard(name)
        plan = self.autoscaler.run_once(
            workloads=workloads, pods_by_job=pods_by_job, pod_nodes=pod_nodes
        )
        if plan is not None and plan.targets:
            # The actuation just changed parallelism: re-list (still
            # O(1)) so the handshake below converges on the NEW values —
            # reconciling against the stale snapshot would POST the old
            # target back and force a spurious world resize.
            workloads = self.cluster.trainer_workloads_map()
        self.reconcile_targets(pods_by_job, workloads)

    def run(self, interval: float = 5.0) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:
                import traceback

                traceback.print_exc()
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        self.autoscaler.stop()

    # -- views ---------------------------------------------------------------
    def job_statuses(self) -> List[dict]:
        out = []
        for job in self.jobs.values():
            s = job.status
            out.append(
                {
                    "name": job.name,
                    "state": s.state.value,
                    "parallelism": s.parallelism,
                    "running": s.running,
                    "pending": s.pending,
                    "pending_seconds": round(
                        s.pending_seconds(now=self._clock()), 3
                    ),
                    "elastic": job.elastic(),
                }
            )
        return out

    def cluster_metrics(self) -> dict:
        """The BASELINE.md north-star aggregates: cluster TPU
        utilization (chips in use / schedulable) and pending-time p50
        across jobs (seconds from submit to first running pod; still-
        pending jobs contribute their elapsed wait)."""
        import statistics

        r = self.cluster.inquiry_resource()
        now = self._clock()
        waits = [
            j.status.pending_seconds(now=now)
            for j in self.jobs.values()
            if j.status.submitted_at > 0
        ]
        return {
            "tpu_total": r.tpu_total,
            "tpu_in_use": r.tpu_request,
            "tpu_utilization": round(
                r.tpu_request / r.tpu_total if r.tpu_total else 0.0, 4
            ),
            "pending_p50_s": round(statistics.median(waits), 3) if waits else 0.0,
            "jobs": len(self.jobs),
        }
