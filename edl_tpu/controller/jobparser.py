"""L2 job parsing: TrainingJob spec -> Kubernetes object manifests.

TPU-native rework of the reference's ``DefaultJobParser``
(``pkg/jobparser.go``).  The reference emitted *three* objects per job —
pserver ReplicaSet (``:74-112``), trainer batch Job (``:115-158``), and
a master ReplicaSet with an etcd v3.2.1 sidecar (``:194-232``).  On TPU
the pserver pool does not exist (gradient sync is an XLA allreduce over
ICI) and the master+etcd pair collapses into one lightweight
coordinator, so a job is exactly **two** manifests:

- trainer batch Job: ``parallelism`` = min_instance, ``RestartPolicy:
  Never`` (ref ``:153`` — scaled-down trainers must not be restarted by
  kubelet), one TPU slice per replica via ``google.com/tpu`` limits and
  GKE TPU nodeSelectors,
- coordinator Deployment of 1 + Service: membership/generation truth
  (replaces master+etcd).

The env contract replaces ``PADDLE_INIT_*`` (ref ``podEnv``,
``:265-313``): trainers get the coordinator address and static job
facts; rank and world size are *not* in env (they are membership facts
owned by the coordinator, because elasticity changes them mid-pod —
the reference's own NOTICE at ``:281-285`` admits its TRAINERS/PSERVERS
envs were wrong under elasticity).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from edl_tpu.cluster.tpu_topology import get_topology
from edl_tpu.resource.training_job import TrainingJob, TPU_RESOURCE_KEY

#: label selecting a job's *trainer* pods (ref label ``paddle-job``,
#: pkg/cluster.go:121).  The coordinator deliberately does NOT carry
#: it — pod counting (``Cluster.job_pods``) keys on this label, and a
#: coordinator counted as a trainer would mask the all-pods-pending
#: signal.  Coordinator objects use OWNER_LABEL instead.
JOB_LABEL = "edl-job"
OWNER_LABEL = "edl-owner"
ROLE_LABEL = "edl-role"
#: replica index label on a multi-host slice's per-replica Job/pods
REPLICA_LABEL = "edl-replica"


def owner_references(job: TrainingJob) -> List[Dict[str, Any]]:
    """ownerReference from the TrainingJob CR, stamped on every rendered
    workload so Kubernetes garbage-collects them when the CR is deleted
    (the reference relied on external cleanup; k8s ownership is the
    native fix — VERDICT r2 #2).  Empty when the CR has no UID yet
    (dry-run rendering before the API server assigned one)."""
    if not job.uid:
        return []
    from edl_tpu.resource.training_job import GROUP, KIND, VERSION

    return [
        {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": KIND,
            "name": job.name,
            "uid": job.uid,
            "controller": True,
            "blockOwnerDeletion": False,
        }
    ]


def pod_env(job: TrainingJob) -> List[Dict[str, Any]]:
    """Trainer-pod environment — the entire runtime contract
    (ref ``podEnv``, ``pkg/jobparser.go:265-313``)."""
    t = job.spec.trainer
    env = [
        {"name": "EDL_JOB_NAME", "value": job.name},
        {"name": "EDL_COORDINATOR_ADDR", "value": f"{job.coordinator_name()}:{job.spec.port}"},
        {"name": "EDL_ENTRYPOINT", "value": t.entrypoint},
        {"name": "EDL_WORKSPACE", "value": t.workspace},
        {"name": "EDL_SLICE_TOPOLOGY", "value": t.slice_topology},
        {"name": "EDL_MIN_INSTANCE", "value": str(t.min_instance)},
        {"name": "EDL_MAX_INSTANCE", "value": str(t.max_instance)},
        {"name": "EDL_NUM_PASSES", "value": str(job.spec.passes)},
        {"name": "EDL_GLOBAL_BATCH_SIZE", "value": str(job.spec.global_batch_size)},
        {"name": "EDL_CHECKPOINT_INTERVAL", "value": str(job.spec.checkpoint_interval_steps)},
        {"name": "EDL_FAULT_TOLERANT", "value": "1" if job.spec.fault_tolerant else "0"},
        {"name": "EDL_DATA_DIR", "value": job.spec.dataset_dir},
        # Durable checkpoint dir (mounted volume): host-DRAM checkpoints
        # spill here; a cold start restores from it (whole-world loss
        # must not restart training at step 0 — the durability the
        # reference's etcd sidecar owned, ref pkg/jobparser.go:174-191).
        {"name": "EDL_CHECKPOINT_DIR", "value": job.spec.checkpoint_dir},
        # Requested mesh layout beyond elastic dp ("fsdp=2,tp=2"; empty
        # = pure dp).  The launcher builds every generation's mesh as
        # dp x <these axes>, dp absorbing the elastic world size.
        {"name": "EDL_PARALLELISM", "value": t.parallelism.env_value()},
        # Persistent XLA compilation cache (mounted volume): joiners and
        # cold starts deserialize previously compiled step executables
        # instead of recompiling inside the resize window (the launcher
        # pins jax_compilation_cache_dir at it).
        {"name": "EDL_COMPILE_CACHE_DIR", "value": job.spec.compile_cache_dir},
        # Shard-only host checkpoints: members hold only their own
        # GSPMD slice + K buddy shards (cluster-memory state; host DRAM
        # never caps model size), spills are per-rank shard files.
        {"name": "EDL_SHARD_ONLY", "value": "1" if job.spec.shard_only else "0"},
        # downward API (ref ``:302-312``)
        {
            "name": "EDL_NAMESPACE",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.namespace"}},
        },
        {
            "name": "EDL_POD_NAME",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
        },
        {
            "name": "EDL_POD_IP",
            "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}},
        },
        # Base port for per-generation jax.distributed worlds; the
        # launcher derives EDL_POD_ADDRESS = $(EDL_POD_IP):$(this).
        {"name": "EDL_JAX_COORD_PORT", "value": "8476"},
    ]
    return env


def _trainer_resources(job: TrainingJob) -> Dict[str, Dict[str, Any]]:
    t = job.spec.trainer
    requests = dict(t.resources.requests)
    limits = dict(t.resources.limits)
    chips = job.tpu_per_trainer()
    if chips:
        limits[TPU_RESOURCE_KEY] = str(chips)
        requests[TPU_RESOURCE_KEY] = str(chips)
    return {"requests": requests, "limits": limits}


def _node_selector(topo) -> Dict[str, str]:
    """GKE TPU scheduling vocabulary: accelerator type + topology."""
    if topo.chips <= 0:
        return {}
    return {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "x".join(
            str(d) for d in topo.ici_mesh
        ),
    }


def _trainer_metadata(
    job: TrainingJob, name: str, labels: Dict[str, str]
) -> Dict[str, Any]:
    metadata: Dict[str, Any] = {
        "name": name,
        "namespace": job.namespace,
        "labels": labels,
    }
    refs = owner_references(job)
    if refs:
        metadata["ownerReferences"] = refs
    return metadata


def _trainer_pod_template(
    job: TrainingJob,
    labels: Dict[str, str],
    extra_env: Optional[List[Dict[str, Any]]] = None,
    subdomain: str = "",
    resources: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The one trainer pod template both renderers share (single-host
    batch Job and multi-host per-replica Indexed Job) — env base, the
    jaxcoord port, volumes, restartPolicy, GKE nodeSelector."""
    topo = get_topology(job.spec.trainer.slice_topology)
    spec: Dict[str, Any] = {
        "restartPolicy": "Never",  # ref :153
        "nodeSelector": _node_selector(topo),
        "containers": [
            {
                "name": "trainer",
                "image": job.spec.image,
                "command": ["python", "-m", "edl_tpu.launcher"],
                "env": pod_env(job) + list(extra_env or ()),
                "resources": (
                    resources if resources is not None else _trainer_resources(job)
                ),
                "ports": [
                    # ONE port: the jax coordination service (the
                    # reference opened ports_num + ports_num_for_sparse
                    # pserver ports, :237-249 — none of that exists on
                    # TPU)
                    {"name": "jaxcoord", "containerPort": 8476}
                ],
            }
        ],
        "volumes": list(job.spec.volumes),
    }
    if subdomain:
        spec["subdomain"] = subdomain
    return {"metadata": {"labels": dict(labels)}, "spec": spec}


#: Victim coordination depends on this field: the autoscaler gracefully
#: deletes the coordinator-chosen victims BEFORE lowering parallelism.
#: Under the default policy (TerminatingOrFailed) the Job controller
#: would replace still-Terminating victims while parallelism is briefly
#: unchanged, and the subsequent PUT could then kill an active-world
#: member.  "Failed" defers replacement until pods are fully terminal,
#: so active count == parallelism converges without the controller ever
#: choosing a victim (k8s >= 1.28; older servers drop the unknown field
#: and keep the reference's kube-chooses semantics).
_POD_REPLACEMENT_POLICY = "Failed"


def parse_to_trainer(job: TrainingJob) -> Dict[str, Any]:
    """Trainer batch Job manifest for single-host topologies
    (ref ``ParseToTrainer``, ``pkg/jobparser.go:115-158``).  Multi-host
    topologies render per-replica Indexed Jobs instead — use
    ``parse_to_trainer_manifests``."""
    if job.hosts_per_replica() > 1:
        raise ValueError(
            f"slice topology {job.spec.trainer.slice_topology!r} spans "
            f"{job.hosts_per_replica()} hosts; render it with "
            "parse_to_trainer_manifests (per-replica Indexed Jobs)"
        )
    t = job.spec.trainer
    labels = {JOB_LABEL: job.name, ROLE_LABEL: "trainer"}
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": _trainer_metadata(job, job.trainer_job_name(), labels),
        "spec": {
            "parallelism": t.min_instance,
            # completions unset: an elastic pool, not a run-to-N batch
            "backoffLimit": 0 if not job.spec.fault_tolerant else 1000000,
            "podReplacementPolicy": _POD_REPLACEMENT_POLICY,
            "template": _trainer_pod_template(job, labels),
        },
    }


def parse_to_trainer_slice(job: TrainingJob, replica: int) -> Dict[str, Any]:
    """One trainer REPLICA of a multi-host slice topology: an Indexed
    batch Job of ``hosts`` pods (completions == parallelism == hosts),
    all landing on the same physical slice via the GKE TPU nodeSelector.
    Pod identity inside the replica comes from the completion index
    (k8s injects ``JOB_COMPLETION_INDEX``; the launcher registers it as
    the host index), and the headless trainer Service
    (``parse_to_trainer_manifests``) gives the slice's TPU runtime
    resolvable per-pod hostnames.  The reference's trainer Job was one
    flat pod pool (``pkg/jobparser.go:115-158``) — multi-host slices
    need pod GROUPS, which is why scaling actuates in whole Jobs here
    (see ``Cluster.update_parallelism``)."""
    hosts = job.hosts_per_replica()
    labels = {
        JOB_LABEL: job.name,
        ROLE_LABEL: "trainer",
        REPLICA_LABEL: str(replica),
    }
    base = _trainer_resources(job)
    # Per-POD chips = per-replica chips / hosts (GKE podslice semantics).
    per_host = str(job.tpu_per_host())
    resources = {
        "requests": {**base["requests"], TPU_RESOURCE_KEY: per_host},
        "limits": {**base["limits"], TPU_RESOURCE_KEY: per_host},
    }
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": _trainer_metadata(
            job, f"{job.trainer_job_name()}-{replica}", labels
        ),
        "spec": {
            "completionMode": "Indexed",
            "completions": hosts,
            "parallelism": hosts,
            "backoffLimit": 0 if not job.spec.fault_tolerant else 1000000,
            "podReplacementPolicy": _POD_REPLACEMENT_POLICY,
            "template": _trainer_pod_template(
                job,
                labels,
                extra_env=[{"name": "EDL_REPLICA", "value": str(replica)}],
                subdomain=job.trainer_job_name(),
                resources=resources,
            ),
        },
    }


def parse_to_trainer_manifests(
    job: TrainingJob,
    replicas: int = 0,
    indexes: Optional[List[int]] = None,
) -> List[Dict[str, Any]]:
    """All trainer manifests for a job at ``replicas`` replicas
    (default min_instance).  Single-host: one batch Job whose
    parallelism is the replica count.  Multi-host: one headless Service
    (stable per-pod DNS for the slice runtime) plus one Indexed Job per
    replica — the unit the autoscaler's actuation creates/deletes.
    ``indexes`` overrides WHICH replica indexes to render (a refresh of
    live non-contiguous replicas must re-apply the EXISTING Jobs, not
    conjure fresh low-index ones)."""
    replicas = replicas or job.spec.trainer.min_instance
    if job.hosts_per_replica() == 1:
        m = parse_to_trainer(job)
        m["spec"]["parallelism"] = replicas
        return [m]
    labels = {JOB_LABEL: job.name, ROLE_LABEL: "trainer"}
    headless = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _trainer_metadata(job, job.trainer_job_name(), labels),
        "spec": {
            "clusterIP": "None",
            "selector": dict(labels),
            "ports": [{"name": "jaxcoord", "port": 8476}],
        },
    }
    if indexes is None:
        indexes = list(range(replicas))
    return [headless] + [parse_to_trainer_slice(job, r) for r in indexes]


def parse_to_coordinator(job: TrainingJob) -> List[Dict[str, Any]]:
    """Coordinator Deployment-of-1 + Service (replaces the reference's
    master ReplicaSet + etcd sidecar + hardcoded master resources,
    ``pkg/jobparser.go:160-232``)."""
    labels = {OWNER_LABEL: job.name, ROLE_LABEL: "coordinator"}
    res = job.spec.coordinator.resources
    resources = {
        "requests": dict(res.requests) or {"cpu": "250m", "memory": "256Mi"},
        "limits": dict(res.limits) or {"cpu": "1", "memory": "1Gi"},
    }
    refs = owner_references(job)
    coord_meta: Dict[str, Any] = {
        "name": job.coordinator_name(),
        "namespace": job.namespace,
        "labels": labels,
    }
    if refs:
        coord_meta["ownerReferences"] = refs
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": dict(coord_meta),
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "containers": [
                        {
                            "name": "coordinator",
                            "image": job.spec.image,
                            "command": [
                                "python",
                                "-m",
                                "edl_tpu.runtime.coord_service",
                                "--port",
                                str(job.spec.port),
                                "--min-world",
                                str(job.spec.trainer.min_instance),
                                "--max-world",
                                str(job.spec.trainer.max_instance),
                                # batch-divisibility quantization: without
                                # this a transient membership count (e.g. 5
                                # of 8 pods up) would form an illegal world
                                "--legal-sizes",
                                ",".join(str(w) for w in job.legal_world_sizes()),
                                # generous lease: a resize window (flush
                                # + compile) must not outlive it
                                "--heartbeat-timeout",
                                "30",
                                # multi-host slices: pods group into
                                # replicas of this many hosts
                                "--hosts",
                                str(job.hosts_per_replica()),
                            ],
                            "env": [
                                {"name": "EDL_JOB_NAME", "value": job.name},
                            ],
                            "resources": resources,
                            "ports": [
                                {"name": "coord", "containerPort": job.spec.port}
                            ],
                        }
                    ],
                },
            },
        },
    }
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": dict(coord_meta),
        "spec": {
            "selector": dict(labels),
            "ports": [{"name": "coord", "port": job.spec.port}],
        },
    }
    return [deployment, service]


#: graceful-drain budget for serving replicas (EDL_SERVE_DRAIN_MS) and
#: the pod grace period sized above it: SIGTERM -> drain (close
#: admission, finish in-flight, free KV, deregister) -> exit, with the
#: kubelet's SIGKILL arriving only after the budget + margin
SERVE_DRAIN_MS = 30000
SERVE_TERMINATION_GRACE_S = 45

#: the fleet front door's listen port and routing knobs (the
#: ``EDL_ROUTE_*`` contract ``edl_tpu.serving.router.main`` reads):
#: per-request retry budget, active-probe cadence, and the
#: consecutive-failure count that ejects a replica from rotation
ROUTE_PORT = 7190
ROUTE_RETRY_BUDGET_MS = 10000
ROUTE_PROBE_MS = 500
ROUTE_EJECT_AFTER = 3


def router_pod_env(job: TrainingJob) -> List[Dict[str, Any]]:
    """Router pod environment: the ``EDL_ROUTE_*`` contract
    (``edl_tpu.serving.router.main`` reads it) plus the serving
    coordinator address the router feeds from — plan membership,
    merged telemetry, and drain flight events all come from there."""
    return [
        {"name": "EDL_JOB_NAME", "value": job.name},
        {
            "name": "EDL_COORDINATOR_ADDR",
            "value": f"{job.serving_coordinator_name()}:{job.spec.port}",
        },
        {"name": "EDL_ROUTE_PORT", "value": str(ROUTE_PORT)},
        {
            "name": "EDL_ROUTE_RETRY_BUDGET_MS",
            "value": str(ROUTE_RETRY_BUDGET_MS),
        },
        {"name": "EDL_ROUTE_PROBE_MS", "value": str(ROUTE_PROBE_MS)},
        {
            "name": "EDL_ROUTE_EJECT_AFTER",
            "value": str(ROUTE_EJECT_AFTER),
        },
    ]


def serving_pod_env(job: TrainingJob) -> List[Dict[str, Any]]:
    """Serving-replica pod environment: the ``EDL_SERVE_*`` contract
    (``edl_tpu.serving.server.serve_run`` reads it) plus the shared
    facts serving inherits from the job — entrypoint (the model to
    serve), the durable checkpoint dir (the weights source training
    spills into), and the compile cache (a restarted replica
    deserializes its bucketed forwards instead of recompiling)."""
    sv = job.spec.serving
    t = job.spec.trainer
    return [
        {"name": "EDL_JOB_NAME", "value": job.name},
        {
            "name": "EDL_COORDINATOR_ADDR",
            "value": f"{job.serving_coordinator_name()}:{job.spec.port}",
        },
        {"name": "EDL_ENTRYPOINT", "value": t.entrypoint},
        {"name": "EDL_WORKSPACE", "value": t.workspace},
        {"name": "EDL_CHECKPOINT_DIR", "value": job.spec.checkpoint_dir},
        {"name": "EDL_COMPILE_CACHE_DIR", "value": job.spec.compile_cache_dir},
        {"name": "EDL_SERVE_PORT", "value": str(sv.port)},
        {"name": "EDL_SERVE_MAX_BATCH", "value": str(sv.max_batch)},
        {"name": "EDL_SERVE_QUEUE_LIMIT", "value": str(sv.queue_limit)},
        {"name": "EDL_SERVE_DEADLINE_MS", "value": str(sv.deadline_ms)},
        # graceful-drain budget: the SIGTERM handler closes admission
        # and lets in-flight generations finish for this long before
        # the replica exits (terminationGracePeriodSeconds below is
        # sized ABOVE it so the kubelet's SIGKILL never beats a drain)
        {"name": "EDL_SERVE_DRAIN_MS", "value": str(SERVE_DRAIN_MS)},
        {
            "name": "EDL_POD_NAME",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
        },
    ]


def parse_to_serving_manifests(job: TrainingJob) -> List[Dict[str, Any]]:
    """Serving fleet manifests (empty when ``spec.serving`` is unset):

    - a SEPARATE serving coordinator Deployment-of-1 + Service — the
      serving world's membership/telemetry truth.  Separate on purpose:
      a serving replica registering with the *training* coordinator
      would join the training plan's rank order and drag inference pods
      through training resize barriers;
    - the replica Deployment (``min_replicas``; the autoscaler's
      serving lane drives the coordinator target between min and max,
      and the Deployment's replica count follows via the lane's kube
      glue) + the front Service routing ``/predict``.
    """
    if job.spec.serving is None:
        return []
    sv = job.spec.serving
    coord_labels = {OWNER_LABEL: job.name, ROLE_LABEL: "serve-coordinator"}
    refs = owner_references(job)

    def meta(name: str, labels: Dict[str, str]) -> Dict[str, Any]:
        m: Dict[str, Any] = {
            "name": name,
            "namespace": job.namespace,
            "labels": dict(labels),
        }
        if refs:
            m["ownerReferences"] = refs
        return m

    coord = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": meta(job.serving_coordinator_name(), coord_labels),
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": dict(coord_labels)},
            "template": {
                "metadata": {"labels": dict(coord_labels)},
                "spec": {
                    "containers": [
                        {
                            "name": "coordinator",
                            "image": job.spec.image,
                            "command": [
                                "python",
                                "-m",
                                "edl_tpu.runtime.coord_service",
                                "--port",
                                str(job.spec.port),
                                "--min-world",
                                str(sv.min_replicas),
                                "--max-world",
                                str(sv.max_replicas),
                                "--heartbeat-timeout",
                                "30",
                            ],
                            "env": [
                                {"name": "EDL_JOB_NAME", "value": job.name}
                            ],
                            "ports": [
                                {
                                    "name": "coord",
                                    "containerPort": job.spec.port,
                                }
                            ],
                        }
                    ],
                },
            },
        },
    }
    coord_svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": meta(job.serving_coordinator_name(), coord_labels),
        "spec": {
            "selector": dict(coord_labels),
            "ports": [{"name": "coord", "port": job.spec.port}],
        },
    }
    labels = {OWNER_LABEL: job.name, ROLE_LABEL: "server"}
    res = sv.resources
    resources = {
        "requests": dict(res.requests) or {"cpu": "1", "memory": "2Gi"},
        "limits": dict(res.limits) or {"cpu": "2", "memory": "4Gi"},
    }
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": meta(job.serving_name(), labels),
        "spec": {
            "replicas": sv.min_replicas,
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    # pod deletion = SIGTERM -> graceful drain; SIGKILL
                    # only after the drain budget + margin has passed
                    "terminationGracePeriodSeconds": (
                        SERVE_TERMINATION_GRACE_S
                    ),
                    "containers": [
                        {
                            "name": "server",
                            "image": job.spec.image,
                            "command": [
                                "python",
                                "-m",
                                "edl_tpu.serving.server",
                            ],
                            "env": serving_pod_env(job),
                            "resources": resources,
                            "ports": [
                                {
                                    "name": "predict",
                                    "containerPort": sv.port,
                                }
                            ],
                        }
                    ],
                    "volumes": list(job.spec.volumes),
                },
            },
        },
    }
    front = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": meta(job.serving_name(), labels),
        "spec": {
            "selector": dict(labels),
            "ports": [{"name": "predict", "port": sv.port}],
        },
    }
    # The fleet front door (ISSUE 20): a routerd Deployment-of-1 + the
    # Service clients actually point at.  Replicas keep their own
    # Service (the router dials them by plan address, and the lane's
    # kube glue still needs it), but the published entry point is the
    # router — it steers around drains, absorbs replica churn, and
    # re-drives cut streams so clients never see the 503s beneath it.
    router_labels = {OWNER_LABEL: job.name, ROLE_LABEL: "router"}
    router = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": meta(job.router_name(), router_labels),
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": dict(router_labels)},
            "template": {
                "metadata": {"labels": dict(router_labels)},
                "spec": {
                    "containers": [
                        {
                            "name": "router",
                            "image": job.spec.image,
                            "command": [
                                "python",
                                "-m",
                                "edl_tpu.serving.router",
                            ],
                            "env": router_pod_env(job),
                            "ports": [
                                {
                                    "name": "route",
                                    "containerPort": ROUTE_PORT,
                                }
                            ],
                        }
                    ],
                },
            },
        },
    }
    router_svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": meta(job.router_name(), router_labels),
        "spec": {
            "selector": dict(router_labels),
            "ports": [{"name": "route", "port": ROUTE_PORT}],
        },
    }
    return [coord, coord_svc, deployment, front, router, router_svc]


class JobParser:
    """ref ``JobParser`` interface (``pkg/jobparser.go:36-41``), minus
    ``ParseToPserver`` (no pservers on TPU).  ``validate`` lives on the
    TrainingJob itself (``resource/training_job.py``)."""

    def validate(self, job: TrainingJob) -> TrainingJob:
        return job.validate()

    def parse_to_trainer(self, job: TrainingJob) -> Dict[str, Any]:
        return parse_to_trainer(job)

    def parse_to_coordinator(self, job: TrainingJob) -> List[Dict[str, Any]]:
        return parse_to_coordinator(job)
