"""L2 job parsing: TrainingJob spec -> Kubernetes object manifests.

TPU-native rework of the reference's ``DefaultJobParser``
(``pkg/jobparser.go``).  The reference emitted *three* objects per job —
pserver ReplicaSet (``:74-112``), trainer batch Job (``:115-158``), and
a master ReplicaSet with an etcd v3.2.1 sidecar (``:194-232``).  On TPU
the pserver pool does not exist (gradient sync is an XLA allreduce over
ICI) and the master+etcd pair collapses into one lightweight
coordinator, so a job is exactly **two** manifests:

- trainer batch Job: ``parallelism`` = min_instance, ``RestartPolicy:
  Never`` (ref ``:153`` — scaled-down trainers must not be restarted by
  kubelet), one TPU slice per replica via ``google.com/tpu`` limits and
  GKE TPU nodeSelectors,
- coordinator Deployment of 1 + Service: membership/generation truth
  (replaces master+etcd).

The env contract replaces ``PADDLE_INIT_*`` (ref ``podEnv``,
``:265-313``): trainers get the coordinator address and static job
facts; rank and world size are *not* in env (they are membership facts
owned by the coordinator, because elasticity changes them mid-pod —
the reference's own NOTICE at ``:281-285`` admits its TRAINERS/PSERVERS
envs were wrong under elasticity).
"""

from __future__ import annotations

from typing import Any, Dict, List

from edl_tpu.cluster.tpu_topology import get_topology
from edl_tpu.resource.training_job import TrainingJob, TPU_RESOURCE_KEY

#: label selecting a job's *trainer* pods (ref label ``paddle-job``,
#: pkg/cluster.go:121).  The coordinator deliberately does NOT carry
#: it — pod counting (``Cluster.job_pods``) keys on this label, and a
#: coordinator counted as a trainer would mask the all-pods-pending
#: signal.  Coordinator objects use OWNER_LABEL instead.
JOB_LABEL = "edl-job"
OWNER_LABEL = "edl-owner"
ROLE_LABEL = "edl-role"


def owner_references(job: TrainingJob) -> List[Dict[str, Any]]:
    """ownerReference from the TrainingJob CR, stamped on every rendered
    workload so Kubernetes garbage-collects them when the CR is deleted
    (the reference relied on external cleanup; k8s ownership is the
    native fix — VERDICT r2 #2).  Empty when the CR has no UID yet
    (dry-run rendering before the API server assigned one)."""
    if not job.uid:
        return []
    from edl_tpu.resource.training_job import GROUP, KIND, VERSION

    return [
        {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": KIND,
            "name": job.name,
            "uid": job.uid,
            "controller": True,
            "blockOwnerDeletion": False,
        }
    ]


def pod_env(job: TrainingJob) -> List[Dict[str, Any]]:
    """Trainer-pod environment — the entire runtime contract
    (ref ``podEnv``, ``pkg/jobparser.go:265-313``)."""
    t = job.spec.trainer
    env = [
        {"name": "EDL_JOB_NAME", "value": job.name},
        {"name": "EDL_COORDINATOR_ADDR", "value": f"{job.coordinator_name()}:{job.spec.port}"},
        {"name": "EDL_ENTRYPOINT", "value": t.entrypoint},
        {"name": "EDL_WORKSPACE", "value": t.workspace},
        {"name": "EDL_SLICE_TOPOLOGY", "value": t.slice_topology},
        {"name": "EDL_MIN_INSTANCE", "value": str(t.min_instance)},
        {"name": "EDL_MAX_INSTANCE", "value": str(t.max_instance)},
        {"name": "EDL_NUM_PASSES", "value": str(job.spec.passes)},
        {"name": "EDL_GLOBAL_BATCH_SIZE", "value": str(job.spec.global_batch_size)},
        {"name": "EDL_CHECKPOINT_INTERVAL", "value": str(job.spec.checkpoint_interval_steps)},
        {"name": "EDL_FAULT_TOLERANT", "value": "1" if job.spec.fault_tolerant else "0"},
        # downward API (ref ``:302-312``)
        {
            "name": "EDL_NAMESPACE",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.namespace"}},
        },
        {
            "name": "EDL_POD_NAME",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
        },
        {
            "name": "EDL_POD_IP",
            "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}},
        },
        # Base port for per-generation jax.distributed worlds; the
        # launcher derives EDL_POD_ADDRESS = $(EDL_POD_IP):$(this).
        {"name": "EDL_JAX_COORD_PORT", "value": "8476"},
    ]
    return env


def _trainer_resources(job: TrainingJob) -> Dict[str, Dict[str, Any]]:
    t = job.spec.trainer
    requests = dict(t.resources.requests)
    limits = dict(t.resources.limits)
    chips = job.tpu_per_trainer()
    if chips:
        limits[TPU_RESOURCE_KEY] = str(chips)
        requests[TPU_RESOURCE_KEY] = str(chips)
    return {"requests": requests, "limits": limits}


def parse_to_trainer(job: TrainingJob) -> Dict[str, Any]:
    """Trainer batch Job manifest (ref ``ParseToTrainer``,
    ``pkg/jobparser.go:115-158``)."""
    t = job.spec.trainer
    topo = get_topology(t.slice_topology)
    labels = {JOB_LABEL: job.name, ROLE_LABEL: "trainer"}
    node_selector: Dict[str, str] = {}
    if topo.chips > 0:
        # GKE TPU scheduling vocabulary: accelerator type + topology.
        node_selector = {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "x".join(
                str(d) for d in topo.ici_mesh
            ),
        }
    metadata: Dict[str, Any] = {
        "name": job.trainer_job_name(),
        "namespace": job.namespace,
        "labels": labels,
    }
    refs = owner_references(job)
    if refs:
        metadata["ownerReferences"] = refs
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": metadata,
        "spec": {
            "parallelism": t.min_instance,
            # completions unset: an elastic pool, not a run-to-N batch
            "backoffLimit": 0 if not job.spec.fault_tolerant else 1000000,
            # Victim coordination depends on this: the autoscaler
            # gracefully deletes the coordinator-chosen victims BEFORE
            # lowering parallelism.  Under the default policy
            # (TerminatingOrFailed) the Job controller would replace
            # still-Terminating victims while parallelism is briefly
            # unchanged, and the subsequent PUT could then kill an
            # active-world member.  "Failed" defers replacement until
            # pods are fully terminal, so active count == parallelism
            # converges without the controller ever choosing a victim
            # (k8s >= 1.28; older servers drop the unknown field and
            # keep the reference's kube-chooses semantics).
            "podReplacementPolicy": "Failed",
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "restartPolicy": "Never",  # ref :153
                    "nodeSelector": node_selector,
                    "containers": [
                        {
                            "name": "trainer",
                            "image": job.spec.image,
                            "command": [
                                "python",
                                "-m",
                                "edl_tpu.launcher",
                            ],
                            "env": pod_env(job),
                            "resources": _trainer_resources(job),
                            "ports": [
                                # ONE port: the jax coordination service
                                # (the reference opened ports_num +
                                # ports_num_for_sparse pserver ports,
                                # :237-249 — none of that exists on TPU)
                                {"name": "jaxcoord", "containerPort": 8476}
                            ],
                        }
                    ],
                    "volumes": list(job.spec.volumes),
                },
            },
        },
    }


def parse_to_coordinator(job: TrainingJob) -> List[Dict[str, Any]]:
    """Coordinator Deployment-of-1 + Service (replaces the reference's
    master ReplicaSet + etcd sidecar + hardcoded master resources,
    ``pkg/jobparser.go:160-232``)."""
    labels = {OWNER_LABEL: job.name, ROLE_LABEL: "coordinator"}
    res = job.spec.coordinator.resources
    resources = {
        "requests": dict(res.requests) or {"cpu": "250m", "memory": "256Mi"},
        "limits": dict(res.limits) or {"cpu": "1", "memory": "1Gi"},
    }
    refs = owner_references(job)
    coord_meta: Dict[str, Any] = {
        "name": job.coordinator_name(),
        "namespace": job.namespace,
        "labels": labels,
    }
    if refs:
        coord_meta["ownerReferences"] = refs
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": dict(coord_meta),
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "containers": [
                        {
                            "name": "coordinator",
                            "image": job.spec.image,
                            "command": [
                                "python",
                                "-m",
                                "edl_tpu.runtime.coord_service",
                                "--port",
                                str(job.spec.port),
                                "--min-world",
                                str(job.spec.trainer.min_instance),
                                "--max-world",
                                str(job.spec.trainer.max_instance),
                                # batch-divisibility quantization: without
                                # this a transient membership count (e.g. 5
                                # of 8 pods up) would form an illegal world
                                "--legal-sizes",
                                ",".join(str(w) for w in job.legal_world_sizes()),
                                # generous lease: a resize window (flush
                                # + compile) must not outlive it
                                "--heartbeat-timeout",
                                "30",
                            ],
                            "env": [
                                {"name": "EDL_JOB_NAME", "value": job.name},
                            ],
                            "resources": resources,
                            "ports": [
                                {"name": "coord", "containerPort": job.spec.port}
                            ],
                        }
                    ],
                },
            },
        },
    }
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": dict(coord_meta),
        "spec": {
            "selector": dict(labels),
            "ports": [{"name": "coord", "port": job.spec.port}],
        },
    }
    return [deployment, service]


class JobParser:
    """ref ``JobParser`` interface (``pkg/jobparser.go:36-41``), minus
    ``ParseToPserver`` (no pservers on TPU).  ``validate`` lives on the
    TrainingJob itself (``resource/training_job.py``)."""

    def validate(self, job: TrainingJob) -> TrainingJob:
        return job.validate()

    def parse_to_trainer(self, job: TrainingJob) -> Dict[str, Any]:
        return parse_to_trainer(job)

    def parse_to_coordinator(self, job: TrainingJob) -> List[Dict[str, Any]]:
        return parse_to_coordinator(job)
