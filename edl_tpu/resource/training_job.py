"""L0 resource model: the TrainingJob API surface.

TPU-native equivalent of the reference CRD types in
``pkg/resource/training_job.go``:

- ``TrainingJob{TypeMeta, ObjectMeta, Spec, Status}``    (ref ``:101-106``)
- ``TrainingJobSpec`` image/port/fault_tolerant/passes   (ref ``:110-124``)
- ``TrainerSpec{Entrypoint, Workspace, Min, Max, Res}``  (ref ``:128-134``)
- ``MasterSpec`` -> ``CoordinatorSpec``                  (ref ``:146-149``)
- status states Created/Running/Failed/Succeed           (ref ``:162-167``)
- helpers ``Elastic()`` / ``GPU()`` / ``NeedGPU()``      (ref ``:179-197``)

Deliberate departures (TPU-first redesign, not translation):

- **No PserverSpec.** The reference's parameter-server ReplicaSet
  (ref ``:138-142``, ``pkg/jobparser.go:74-112``) exists only to sync
  gradients over TCP; on TPU that is an XLA allreduce over ICI inside
  the jitted train step, so there is no pserver process to declare.
- **TPU chips, not nvidia-gpu.** Device accounting keys on
  ``google.com/tpu`` (the reference used the long-deprecated
  ``alpha.kubernetes.io/nvidia-gpu``, ref ``:74,185`` — a quirk
  SURVEY.md says to fix, not replicate).
- **Slice topology.** A trainer replica is one TPU slice, not one GPU
  pod; the spec names the per-replica topology (e.g. ``"v5e-4"``) so
  scaling deltas are quantized to whole slices.
- **Status is real.** The reference defines ``TrainingJobStatus`` but
  never writes it (SURVEY.md §5.5); our controller maintains it as a
  state machine Created -> Running -> (Scaling <->) -> Succeed/Failed.

API group: ``edl.tpu.dev/v1`` (analog of ``paddlepaddle.org/v1``,
ref ``:208-228``).
"""

from __future__ import annotations

import copy
import enum
import time
from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Mapping, Optional

from edl_tpu.utils.quantity import (
    parse_cpu_milli,
    parse_memory_mega,
    parse_count,
)

GROUP = "edl.tpu.dev"
VERSION = "v1"
KIND = "TrainingJob"
PLURAL = "trainingjobs"

#: Device resource key used for inventory + limits.
TPU_RESOURCE_KEY = "google.com/tpu"

#: Defaults mirroring DefaultJobParser.Validate (ref pkg/jobparser.go:47-71).
DEFAULT_PORT = 7164
DEFAULT_IMAGE = "edl-tpu/trainer:latest"
DEFAULT_PASSES = 1


class ValidationError(ValueError):
    """Raised when a TrainingJob spec is invalid (ref pkg/jobparser.go:66-68)."""


class JobState(str, enum.Enum):
    """Job lifecycle states (ref pkg/resource/training_job.go:162-167, plus
    SCALING which the reference lacked because it never wrote status)."""

    CREATED = "Created"
    RUNNING = "Running"
    SCALING = "Scaling"
    SUCCEED = "Succeed"
    FAILED = "Failed"


@dataclass
class ResourceSpec:
    """Requests/limits as k8s-style quantity strings.

    Normalized accessors mirror the reference's per-job accessors
    (ref pkg/autoscaler.go:39-52)."""

    requests: Dict[str, Any] = field(default_factory=dict)
    limits: Dict[str, Any] = field(default_factory=dict)

    # -- normalized views ---------------------------------------------------
    def cpu_request_milli(self) -> int:
        return parse_cpu_milli(self.requests.get("cpu", 0))

    def cpu_limit_milli(self) -> int:
        return parse_cpu_milli(self.limits.get("cpu", 0))

    def mem_request_mega(self) -> int:
        return parse_memory_mega(self.requests.get("memory", 0))

    def mem_limit_mega(self) -> int:
        return parse_memory_mega(self.limits.get("memory", 0))

    def tpu_limit(self) -> int:
        """TPU chips per replica (ref analog: TrainerGPULimit,
        pkg/autoscaler.go:39-42, reading the device limit)."""
        return parse_count(self.limits.get(TPU_RESOURCE_KEY, 0))

    def normalized(self) -> Dict[str, Dict[str, int]]:
        return {
            "requests": {
                "cpu_milli": self.cpu_request_milli(),
                "memory_mega": self.mem_request_mega(),
            },
            "limits": {
                "cpu_milli": self.cpu_limit_milli(),
                "memory_mega": self.mem_limit_mega(),
                "tpu": self.tpu_limit(),
            },
        }

    @staticmethod
    def from_dict(d: Optional[Mapping[str, Any]]) -> "ResourceSpec":
        d = d or {}
        return ResourceSpec(
            requests=dict(d.get("requests", {}) or {}),
            limits=dict(d.get("limits", {}) or {}),
        )


#: Model-parallel mesh axes a job may request in its layout.  ``dp`` is
#: deliberately NOT here: it is the elastic axis, always the remainder
#: (world x chips / product of the requested axes), so the layout stays
#: valid at every legal world size.
LAYOUT_AXES = ("fsdp", "tp", "sp", "ep", "pp")
#: Layout axes that carry batch rows: the global batch shards over
#: dp x fsdp; tp/sp/ep/pp replicate the batch (they split hidden dims,
#: sequence, experts, and stages respectively).
BATCH_LAYOUT_AXES = ("fsdp",)


@dataclass
class ParallelismSpec:
    """Requested parallelism layout: model-axis sizes for the trainer
    mesh (the reference's trainer spec was its whole parallelism
    interface — one flat pool of data-parallel pods,
    ref pkg/resource/training_job.go:128-134; this spec is its TPU-first
    generalization to dp x fsdp x tp x sp x ep x pp meshes).

    All sizes default to 1 (pure elastic data parallelism — the
    reference's one strategy).  The ``dp`` extent is never declared:
    at world size ``w`` with ``c`` chips per replica it is
    ``w*c / product()``, so elasticity resizes dp and leaves the model
    axes fixed."""

    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def axes(self) -> Dict[str, int]:
        """The non-trivial axis sizes (size > 1) — the dict handed to
        mesh construction and rendered into EDL_PARALLELISM."""
        return {
            a: int(getattr(self, a))
            for a in LAYOUT_AXES
            if int(getattr(self, a)) > 1
        }

    def product(self) -> int:
        """Devices one dp slot spans: the model-axis product."""
        out = 1
        for a in LAYOUT_AXES:
            out *= max(1, int(getattr(self, a)))
        return out

    def nonbatch_product(self) -> int:
        """Product of axes that replicate the batch (tp*sp*ep*pp);
        total devices / this = the batch extent (dp*fsdp)."""
        out = 1
        for a in LAYOUT_AXES:
            if a not in BATCH_LAYOUT_AXES:
                out *= max(1, int(getattr(self, a)))
        return out

    def trivial(self) -> bool:
        return self.product() == 1

    def env_value(self) -> str:
        """Serialized for the EDL_PARALLELISM pod env: "fsdp=2,tp=2"."""
        return ",".join(f"{a}={s}" for a, s in self.axes().items())

    @staticmethod
    def from_env(value: str) -> "ParallelismSpec":
        sizes: Dict[str, int] = {}
        for part in (value or "").split(","):
            part = part.strip()
            if not part:
                continue
            axis, _, size = part.partition("=")
            sizes[axis.strip()] = int(size)
        return ParallelismSpec.from_dict(sizes)

    @staticmethod
    def from_dict(d: Optional[Mapping[str, Any]]) -> "ParallelismSpec":
        d = d or {}
        unknown = set(d) - set(LAYOUT_AXES)
        if unknown:
            raise ValidationError(
                f"unknown parallelism axes {sorted(unknown)}; "
                f"valid: {list(LAYOUT_AXES)} (dp is implicit — it is the "
                "elastic remainder)"
            )
        return ParallelismSpec(**{a: int(s) for a, s in d.items()})


@dataclass
class TrainerSpec:
    """Elastic trainer group (ref TrainerSpec, pkg/resource/training_job.go:128-134).

    ``min_instance``/``max_instance`` count *trainer replicas*; each
    replica owns one TPU slice of ``slice_topology``."""

    entrypoint: str = ""
    workspace: str = ""
    min_instance: int = 1
    max_instance: int = 1
    #: Per-replica TPU slice topology, e.g. "v5e-1", "v5e-4", "v5e-8".
    slice_topology: str = "v5e-4"
    resources: ResourceSpec = field(default_factory=ResourceSpec)
    #: Requested mesh layout beyond elastic dp (fsdp/tp/sp/ep/pp).
    parallelism: ParallelismSpec = field(default_factory=ParallelismSpec)

    @staticmethod
    def from_dict(d: Optional[Mapping[str, Any]]) -> "TrainerSpec":
        d = d or {}
        return TrainerSpec(
            entrypoint=d.get("entrypoint", ""),
            workspace=d.get("workspace", ""),
            min_instance=int(d.get("min_instance", d.get("minInstance", 1))),
            max_instance=int(d.get("max_instance", d.get("maxInstance", 1))),
            slice_topology=d.get("slice_topology", d.get("sliceTopology", "v5e-4")),
            resources=ResourceSpec.from_dict(d.get("resources")),
            parallelism=ParallelismSpec.from_dict(d.get("parallelism")),
        )


@dataclass
class CoordinatorSpec:
    """Elastic coordinator (replaces the reference's master ReplicaSet +
    etcd v3.2.1 sidecar, ref MasterSpec pkg/resource/training_job.go:146-149
    and pkg/jobparser.go:174-232).  One lightweight process that tracks
    membership generations, assigns data shards, and indexes checkpoints;
    backed by the JAX coordination service instead of etcd.  It listens
    on ``TrainingJobSpec.port`` — the job's single port."""

    resources: ResourceSpec = field(default_factory=ResourceSpec)

    @staticmethod
    def from_dict(d: Optional[Mapping[str, Any]]) -> "CoordinatorSpec":
        d = d or {}
        return CoordinatorSpec(
            resources=ResourceSpec.from_dict(d.get("resources")),
        )


@dataclass
class ServingSpec:
    """Elastic inference serving attached to a TrainingJob: a fleet of
    checkpoint-backed replicas (``edl_tpu.serving``) scaled between
    ``[min_replicas, max_replicas]`` by the autoscaler's serving lane
    on observed p95 latency / queue depth.  Replicas serve the newest
    *verified* checkpoint from ``spec.checkpoint_dir`` and hot-swap as
    training spills fresher ones — train and serve as one substrate
    (Pathways, PAPERS.md), sharing image, volumes, and control plane."""

    min_replicas: int = 1
    max_replicas: int = 1
    port: int = 7180
    max_batch: int = 64
    queue_limit: int = 256
    deadline_ms: int = 2000
    resources: ResourceSpec = field(default_factory=ResourceSpec)

    @staticmethod
    def from_dict(d: Optional[Mapping[str, Any]]) -> Optional["ServingSpec"]:
        if not d:
            return None
        return ServingSpec(
            min_replicas=int(d.get("min_replicas", d.get("minReplicas", 1))),
            max_replicas=int(d.get("max_replicas", d.get("maxReplicas", 1))),
            port=int(d.get("port", 7180)),
            max_batch=int(d.get("max_batch", d.get("maxBatch", 64))),
            queue_limit=int(d.get("queue_limit", d.get("queueLimit", 256))),
            deadline_ms=int(d.get("deadline_ms", d.get("deadlineMs", 2000))),
            resources=ResourceSpec.from_dict(d.get("resources")),
        )


@dataclass
class TrainingJobSpec:
    """ref TrainingJobSpec (pkg/resource/training_job.go:110-124).

    Dropped fields, by design: ``ports_num`` / ``ports_num_for_sparse``
    (pserver TCP port ranges, ref ``:114-115`` — no pserver exists here;
    the only port is the coordinator's) and per-pod ``volumes`` (carried
    opaquely in ``volumes`` for manifest passthrough)."""

    image: str = ""
    port: int = 0
    fault_tolerant: bool = False
    passes: int = 0
    #: Fleet-arbiter scheduling priority (higher = more important).
    #: When multiple jobs bid for one TPU inventory
    #: (``edl_tpu.fleet``), serving spikes preempt the LOWEST-priority
    #: elastic trainer first, and growth goes to higher priorities
    #: first.  0 is the default tier; the reference had no notion of
    #: cross-job priority (its fixed point ordered purely by
    #: fulfillment, ref ``pkg/autoscaler.go:97-129``).
    priority: int = 0
    trainer: TrainerSpec = field(default_factory=TrainerSpec)
    coordinator: CoordinatorSpec = field(default_factory=CoordinatorSpec)
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    #: Runtime knobs the reference kept outside the CRD (in user code).
    #: Fixed global batch under elasticity (SURVEY.md §7.4): per-replica
    #: batch = global_batch_size / world_size at every generation.
    global_batch_size: int = 0
    checkpoint_interval_steps: int = 100
    #: directory of a file-backed array store (see
    #: ``edl_tpu.runtime.datasets``) mounted into trainer pods; ""
    #: trains on the model's synthetic data (the reference carried the
    #: analogous pointer opaquely in Workspace/TRAINER_PACKAGE,
    #: ref ``pkg/jobparser.go:288-291``)
    dataset_dir: str = ""
    #: durable checkpoint directory (a mounted volume shared by the
    #: trainer pods).  When set, every host-DRAM checkpoint also spills
    #: here and a cold start (whole-world loss: full slice preemption,
    #: restart-all) restores from it instead of silently re-initializing
    #: at step 0.  The reference delegated exactly this durability to
    #: its master+etcd sidecar (ref ``pkg/jobparser.go:174-191``;
    #: design doc pointer ``README.md:18-21``); "" = DRAM-only.
    checkpoint_dir: str = ""
    #: persistent XLA compilation-cache directory (a mounted volume
    #: shared by the trainer pods).  When set, every trainer pins
    #: ``jax_compilation_cache_dir`` at it (launcher wiring via
    #: ``EDL_COMPILE_CACHE_DIR``), so joiners, restarted pods, and
    #: cold-started worlds DESERIALIZE previously compiled step
    #: executables instead of recompiling them — the other half of the
    #: zero-stall resize (the AOT prewarmer removes compiles from warm
    #: resizes; this removes them from cold ones); "" = no cache.
    compile_cache_dir: str = ""
    #: shard-only host checkpoints (EDL_SHARD_ONLY): each dp×fsdp
    #: member's host DRAM holds only its own GSPMD slice plus K
    #: ring-buddy shards — cluster memory, not any one host's DRAM,
    #: bounds model size.  Spills become per-rank shard files whose
    #: union is the durable checkpoint; restores assemble device
    #: slices from resident/peer shards with NO process materializing
    #: full state.  Requires the checkpoint fabric (EDL_FABRIC=1, the
    #: default); False = classic full-copy host checkpoints.
    shard_only: bool = False
    #: elastic inference serving attached to this job (None = train
    #: only).  Serving replicas load the newest verified checkpoint
    #: from ``checkpoint_dir`` and hot-swap as training writes fresher
    #: ones; the autoscaler's serving lane scales them on p95/queue
    #: depth read from the serving coordinator's merged telemetry.
    serving: Optional["ServingSpec"] = None

    @staticmethod
    def from_dict(d: Optional[Mapping[str, Any]]) -> "TrainingJobSpec":
        d = d or {}
        return TrainingJobSpec(
            serving=ServingSpec.from_dict(d.get("serving")),
            dataset_dir=str(d.get("dataset_dir", d.get("datasetDir", "")) or ""),
            checkpoint_dir=str(
                d.get("checkpoint_dir", d.get("checkpointDir", "")) or ""
            ),
            compile_cache_dir=str(
                d.get("compile_cache_dir", d.get("compileCacheDir", "")) or ""
            ),
            shard_only=bool(d.get("shard_only", d.get("shardOnly", False))),
            image=d.get("image", ""),
            port=int(d.get("port", 0)),
            priority=int(d.get("priority", 0)),
            fault_tolerant=bool(d.get("fault_tolerant", d.get("faultTolerant", False))),
            passes=int(d.get("passes", 0)),
            trainer=TrainerSpec.from_dict(d.get("trainer")),
            coordinator=CoordinatorSpec.from_dict(
                d.get("coordinator", d.get("master"))
            ),
            volumes=list(d.get("volumes", []) or []),
            global_batch_size=int(d.get("global_batch_size", d.get("globalBatchSize", 0))),
            checkpoint_interval_steps=int(
                d.get("checkpoint_interval_steps", d.get("checkpointIntervalSteps", 100))
            ),
        )


@dataclass
class TrainingJobStatus:
    """ref TrainingJobStatus (pkg/resource/training_job.go:153-167).
    The reference never writes it (SURVEY.md §5.5); ours is maintained by
    the controller."""

    state: JobState = JobState.CREATED
    parallelism: int = 0
    generation: int = 0
    running: int = 0
    pending: int = 0
    message: str = ""
    #: wall-clock seconds the job spent with all pods pending (for the
    #: pending-time p50 north-star metric).
    submitted_at: float = 0.0
    started_at: float = 0.0

    def pending_seconds(self, now: Optional[float] = None) -> float:
        """Seconds from submit to first running pod.  ``now`` must come
        from the same clock that wrote the timestamps (the controller
        passes its injected clock); defaults to wall time."""
        if self.submitted_at <= 0:
            return 0.0
        if self.started_at > 0:
            end = self.started_at
        else:
            end = now if now is not None else time.time()
        return max(0.0, end - self.submitted_at)


@dataclass
class TrainingJob:
    """ref TrainingJob (pkg/resource/training_job.go:101-106)."""

    name: str = ""
    namespace: str = "default"
    #: API-server-assigned object UID; stamps ownerReferences on every
    #: rendered workload manifest so Kubernetes garbage-collects them
    #: when the CR is deleted (the ref delegated GC to k8s ownership).
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    spec: TrainingJobSpec = field(default_factory=TrainingJobSpec)
    status: TrainingJobStatus = field(default_factory=TrainingJobStatus)

    # -- helpers (ref pkg/resource/training_job.go:179-197) -----------------
    def elastic(self) -> bool:
        """min < max (ref Elastic(), ``:179-181``)."""
        return self.spec.trainer.min_instance < self.spec.trainer.max_instance

    def tpu_per_trainer(self) -> int:
        """TPU chips each trainer replica consumes (ref GPU(), ``:184-190``,
        reading the nvidia limit).  Falls back to the slice topology's
        chip count when resources.limits omits the key."""
        n = self.spec.trainer.resources.tpu_limit()
        if n:
            return n
        from edl_tpu.cluster.tpu_topology import topology_chips

        return topology_chips(self.spec.trainer.slice_topology)

    def need_tpu(self) -> bool:
        """ref NeedGPU() (``:193-197``)."""
        return self.tpu_per_trainer() > 0

    def hosts_per_replica(self) -> int:
        """Host machines (pods) per trainer replica.  1 for single-host
        slices; >1 for multi-host topologies (v5e-16 = 2 hosts), where
        one replica renders as an Indexed Job of this many pods."""
        from edl_tpu.cluster.tpu_topology import get_topology

        try:
            return max(1, get_topology(self.spec.trainer.slice_topology).hosts)
        except ValueError:
            return 1

    def tpu_per_host(self) -> int:
        """TPU chips each POD requests: a multi-host replica's chips
        split across its host pods (GKE podslice semantics: the per-pod
        ``google.com/tpu`` limit is chips-per-host)."""
        return self.tpu_per_trainer() // self.hosts_per_replica()

    def fullname(self) -> str:
        return f"{self.namespace}/{self.name}"

    def trainer_job_name(self) -> str:
        """Name of the actuated trainer workload: ``<job>-trainer``
        (ref pkg/cluster.go:92-94)."""
        return f"{self.name}-trainer"

    def coordinator_name(self) -> str:
        return f"{self.name}-coordinator"

    def serving_name(self) -> str:
        """Name of the serving-replica Deployment/Service:
        ``<job>-serve``."""
        return f"{self.name}-serve"

    def serving_coordinator_name(self) -> str:
        """The SERVING world's coordinator (separate from the training
        coordinator: serving replicas must never join the training
        plan's rank order)."""
        return f"{self.name}-serve-coordinator"

    def router_name(self) -> str:
        """The fleet front door (routerd) Deployment/Service:
        ``<job>-router`` — what clients actually point at."""
        return f"{self.name}-router"

    # -- validation + defaulting (ref DefaultJobParser.Validate,
    #    pkg/jobparser.go:47-71) --------------------------------------------
    def validate(self) -> "TrainingJob":
        """Fill defaults and reject invalid specs.  Returns self.

        Mirrors ref semantics: default port/image/passes; reject
        elastic-without-fault-tolerant (ref ``:66-68``).  Adds TPU
        constraints the reference could not have: instance bounds sane,
        topology legal."""
        s = self.spec
        if not self.name:
            raise ValidationError("job name must be set")
        if s.port <= 0:
            s.port = DEFAULT_PORT
        if not s.image:
            s.image = DEFAULT_IMAGE
        if s.passes <= 0:
            s.passes = DEFAULT_PASSES
        t = s.trainer
        if t.min_instance <= 0:
            raise ValidationError("trainer.min_instance must be >= 1")
        if t.max_instance < t.min_instance:
            raise ValidationError(
                "trainer.max_instance must be >= trainer.min_instance"
            )
        if self.elastic() and not s.fault_tolerant:
            # ref pkg/jobparser.go:66-68: elastic requires fault tolerance
            # (a shrinkable job must checkpoint + re-mesh).
            raise ValidationError(
                "max_instance > min_instance requires fault_tolerant: true"
            )
        from edl_tpu.cluster.tpu_topology import topology_chips

        try:
            topology_chips(t.slice_topology)
        except ValueError as e:
            raise ValidationError(str(e)) from None
        for res in (t.resources, s.coordinator.resources):
            for bucket in (res.requests, res.limits):
                for key, q in bucket.items():
                    try:
                        if key == "cpu":
                            v = parse_cpu_milli(q)
                        elif key == "memory":
                            v = parse_memory_mega(q)
                        else:
                            v = parse_count(q)
                    except (ValueError, TypeError) as e:
                        raise ValidationError(
                            f"invalid quantity {key}={q!r}: {e}"
                        ) from None
                    if v < 0:
                        raise ValidationError(
                            f"resource quantity must be >= 0: {key}={q!r}"
                        )
        declared_tpu = t.resources.tpu_limit()
        topo_chips = topology_chips(t.slice_topology)
        if declared_tpu and declared_tpu != topo_chips:
            raise ValidationError(
                f"limits['{TPU_RESOURCE_KEY}']={declared_tpu} contradicts "
                f"slice_topology {t.slice_topology!r} ({topo_chips} chips); "
                "omit the limit or make them agree"
            )
        if s.global_batch_size < 0:
            raise ValidationError("global_batch_size must be >= 0")
        if s.priority < 0:
            raise ValidationError("priority must be >= 0")
        if s.serving is not None:
            sv = s.serving
            if sv.min_replicas < 1 or sv.max_replicas < sv.min_replicas:
                raise ValidationError(
                    "serving replica bounds must satisfy 1 <= min <= max "
                    f"(got [{sv.min_replicas}, {sv.max_replicas}])"
                )
            if sv.max_batch < 1 or sv.queue_limit < 1 or sv.deadline_ms < 1:
                raise ValidationError(
                    "serving max_batch / queue_limit / deadline_ms must "
                    "be >= 1"
                )
            if not s.checkpoint_dir:
                raise ValidationError(
                    "spec.serving requires spec.checkpoint_dir: replicas "
                    "serve the newest verified durable checkpoint (a "
                    "DRAM-only training fleet leaves them nothing to load)"
                )
        par = t.parallelism
        for a in LAYOUT_AXES:
            if int(getattr(par, a)) < 1:
                raise ValidationError(
                    f"parallelism.{a} must be >= 1, got {getattr(par, a)}"
                )
        chips = max(1, topo_chips)
        # The layout and (when set) the global batch must admit BOTH
        # instance endpoints, or the job could neither start at min nor
        # reach max.  At world w the mesh spans w x chips devices, the
        # model axes claim par.product() of them per dp slot, and the
        # batch shards over the dp x fsdp extent (SURVEY.md §7.4:
        # fixed-global-batch elasticity).
        for w, which in ((t.min_instance, "min"), (t.max_instance, "max")):
            total = w * chips
            if total % par.product() != 0:
                raise ValidationError(
                    f"parallelism layout {par.axes()} (product "
                    f"{par.product()}) must divide trainer.{which}_instance "
                    f"x slice chips ({w} x {chips} = {total})"
                )
            if s.global_batch_size:
                extent = total // par.nonbatch_product()
                if s.global_batch_size % extent != 0:
                    raise ValidationError(
                        "global_batch_size must be divisible by the batch "
                        f"extent at trainer.{which}_instance "
                        f"(dp x fsdp = {extent} of {total} devices)"
                    )
        return self

    def legal_world_sizes(
        self, chips_per_replica: Optional[int] = None
    ) -> List[int]:
        """World sizes the elastic runtime may resize to: every w in
        [min_instance, max_instance] whose full device mesh
        (w x chips-per-replica) factors into the requested parallelism
        layout AND whose batch extent (dp x fsdp) divides the global
        batch.  With no global_batch_size set, only the layout
        divisibility applies.

        ``chips_per_replica`` defaults to the spec's slice topology;
        pass 1 when the runtime simulates one-device trainers (the CLI
        local modes), where the deployed topology's chip count would
        wrongly disqualify sizes the actual mesh shards fine."""
        from edl_tpu.cluster.tpu_topology import topology_chips

        t = self.spec.trainer
        if chips_per_replica is None:
            chips_per_replica = topology_chips(t.slice_topology)
        return quantized_world_sizes(
            t.min_instance,
            t.max_instance,
            chips_per_replica,
            self.spec.global_batch_size,
            t.parallelism,
        )

    # -- (de)serialization --------------------------------------------------
    def to_manifest(self) -> Dict[str, Any]:
        """Render as a k8s custom-resource manifest dict."""
        spec = asdict(self.spec)
        status = asdict(self.status)
        status["state"] = self.status.state.value
        metadata = {
            "name": self.name,
            "namespace": self.namespace,
            "labels": dict(self.labels),
        }
        if self.uid:
            metadata["uid"] = self.uid
        return {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": KIND,
            "metadata": metadata,
            "spec": spec,
            "status": status,
        }

    @staticmethod
    def from_manifest(d: Mapping[str, Any]) -> "TrainingJob":
        api_version = d.get("apiVersion", f"{GROUP}/{VERSION}")
        if api_version != f"{GROUP}/{VERSION}":
            raise ValidationError(f"unsupported apiVersion: {api_version}")
        if d.get("kind", KIND) != KIND:
            raise ValidationError(f"unsupported kind: {d.get('kind')}")
        meta = d.get("metadata", {}) or {}
        try:
            job = TrainingJob(
                name=meta.get("name", ""),
                namespace=meta.get("namespace", "default"),
                uid=meta.get("uid", ""),
                labels=dict(meta.get("labels", {}) or {}),
                spec=TrainingJobSpec.from_dict(d.get("spec")),
            )
            st = d.get("status") or {}
            if st:
                job.status = TrainingJobStatus(
                    state=JobState(st.get("state", "Created")),
                    parallelism=int(st.get("parallelism", 0)),
                    generation=int(st.get("generation", 0)),
                    running=int(st.get("running", 0)),
                    pending=int(st.get("pending", 0)),
                    message=st.get("message", ""),
                    submitted_at=float(st.get("submitted_at", 0.0)),
                    started_at=float(st.get("started_at", 0.0)),
                )
        except ValidationError:
            raise
        except (ValueError, TypeError) as e:
            raise ValidationError(f"malformed TrainingJob manifest: {e}") from None
        return job

    @staticmethod
    def from_yaml(text: str) -> "TrainingJob":
        import yaml

        return TrainingJob.from_manifest(yaml.safe_load(text))

    def deepcopy(self) -> "TrainingJob":
        """ref zz_generated.deepcopy.go DeepCopyObject — trivially
        ``copy.deepcopy`` in Python; kept as a named method so call
        sites document intent."""
        return copy.deepcopy(self)


def quantized_world_sizes(
    min_w: int,
    max_w: int,
    chips_per_replica: int,
    global_batch_size: int,
    parallelism: Optional[ParallelismSpec] = None,
) -> List[int]:
    """World sizes in [min_w, max_w] the elastic runtime may form.

    A size ``w`` is legal when its full device mesh (w x chips) factors
    into the parallelism layout (dp = total / product must be whole)
    and, when a global batch is set, the batch extent (dp x fsdp =
    total / nonbatch product) divides it.  Shared by
    ``TrainingJob.legal_world_sizes`` (deployed path: the coordinator's
    ``--legal-sizes``) and the launcher/CLI local modes, so every mode
    quantizes identically."""
    par = parallelism or ParallelismSpec()
    chips = max(1, chips_per_replica)
    out = []
    for w in range(min_w, max_w + 1):
        total = w * chips
        if total % par.product() != 0:
            continue
        if global_batch_size:
            extent = total // par.nonbatch_product()
            if global_batch_size % extent != 0:
                continue
        out.append(w)
    return out


def crd_manifest() -> Dict[str, Any]:
    """CustomResourceDefinition manifest registering TrainingJob
    (ref RegisterResource, pkg/resource/training_job.go:208-228 — the
    reference registers a client-side scheme; on modern k8s the CRD
    itself is an object we can emit)."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{PLURAL}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": f"{KIND}List",
                "plural": PLURAL,
                "singular": "trainingjob",
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields": True,
                                },
                                "status": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields": True,
                                },
                            },
                        }
                    },
                    "subresources": {"status": {}},
                }
            ],
        },
    }
