from edl_tpu.resource.training_job import (
    GROUP,
    VERSION,
    KIND,
    TPU_RESOURCE_KEY,
    JobState,
    ResourceSpec,
    TrainerSpec,
    CoordinatorSpec,
    TrainingJobSpec,
    TrainingJobStatus,
    TrainingJob,
    ValidationError,
)

__all__ = [
    "GROUP",
    "VERSION",
    "KIND",
    "TPU_RESOURCE_KEY",
    "JobState",
    "ResourceSpec",
    "TrainerSpec",
    "CoordinatorSpec",
    "TrainingJobSpec",
    "TrainingJobStatus",
    "TrainingJob",
    "ValidationError",
]
