"""The pjit data-parallel train step — the pserver replacement.

In the reference system a training step's gradient sync crossed process
boundaries: trainer -> pserver TCP push/pull, with pserver count pinned
at job submission (``PADDLE_INIT_NUM_GRADIENT_SERVERS`` fixed to
MinInstance, ``pkg/jobparser.go:298`` — sync SGD wasn't even
elastic-aware, SURVEY.md §7.4).  Here the whole step — forward,
backward, gradient allreduce over ICI, optimizer update — is ONE
XLA-compiled program over a ``jax.sharding.Mesh``: batch sharded on the
``dp`` axis, params replicated (or sharded via the model's partition
rules), XLA inserting the collectives.  Elasticity = constructing a new
``Trainer`` over a different-size mesh and restoring state onto it
(see ``edl_tpu.runtime.elastic``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu import telemetry
from edl_tpu.models.base import ModelDef
from edl_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, filter_partition_spec


@struct.dataclass
class TrainState:
    """Minimal train state pytree: step counter, params, optimizer state.

    Deliberately not flax's TrainState: checkpoint/restore (with
    resharding) wants a plain pytree with no bound apply_fn."""

    step: jax.Array
    params: Any
    opt_state: Any


class Trainer:
    """Compiles and runs the train step for one (model, optimizer, mesh).

    One Trainer == one world-size generation.  On resize, the elastic
    runtime builds a fresh Trainer over the new mesh and moves state
    into it via the checkpoint store.
    """

    def __init__(
        self,
        model: ModelDef,
        optimizer: optax.GradientTransformation,
        mesh: Mesh,
        seed: int = 0,
        donate: bool = True,
        metrics_grad_norm: bool = False,
    ):
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.seed = seed
        self.metrics_grad_norm = metrics_grad_norm
        self._base_rng = jax.random.key(seed)

        # Parameter shardings: model partition rules if provided, else
        # fully replicated (pure DP).
        self._param_spec_fn = model.param_partition
        self._state_shardings = None  # cached after init_state()

        axis_names = mesh.axis_names

        def filter_spec(spec: P) -> P:
            # Shared with the serving plane (parallel.mesh): one rule
            # set serves every mesh — a pure-DP mesh simply ignores
            # tp/fsdp placements, the dp×tp serving mesh ignores fsdp.
            return filter_partition_spec(spec, axis_names)

        def constrain(params):
            """Pin params to the model's partition rules on this mesh;
            XLA's sharding propagation then lays out grads/opt-state to
            match (GSPMD does the work the reference's pserver sharding
            did by hand)."""
            if self._param_spec_fn is None:
                return params
            specs = self._param_spec_fn(params)
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, filter_spec(s)),
                specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.lax.with_sharding_constraint(params, shardings)

        self._constrain = constrain

        def constrain_opt(opt_state, params):
            """Pin optimizer-state subtrees that mirror the params
            pytree (adam's mu/nu) to the params' partition layout.
            Without this, init leaves the moments replicated while the
            step's GSPMD propagation shards them like the grads — the
            state's layout would change between step 0 and step 1,
            silently recompiling the jit path every resize and
            hard-failing the AOT-warmed executable on its second
            call (input shardings no longer match what it was
            compiled for)."""
            if self._param_spec_fn is None:
                return opt_state
            pdef = jax.tree_util.tree_structure(params)

            def mirrors(x):
                return jax.tree_util.tree_structure(x) == pdef

            return jax.tree_util.tree_map(
                lambda sub: constrain(sub) if mirrors(sub) else sub,
                opt_state,
                is_leaf=mirrors,
            )

        def init_fn(rng):
            params = constrain(model.init_params(rng))
            opt_state = constrain_opt(optimizer.init(params), params)
            return TrainState(
                step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state
            )

        self._init_fn = init_fn

        def train_step(state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
            step_rng = jax.random.fold_in(self._base_rng, state.step)

            def loss_of(p):
                loss, aux = model.loss_fn(p, batch, step_rng)
                return loss, aux

            (loss, aux), grads = jax.value_and_grad(loss_of, has_aux=True)(
                state.params
            )
            updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
            new_opt = constrain_opt(new_opt, state.params)
            new_params = constrain(optax.apply_updates(state.params, updates))
            new_state = TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            )
            metrics = dict(aux)
            metrics["loss"] = loss
            if self.metrics_grad_norm:
                # Off by default: a tree-wide norm is ~260 small
                # reductions per step — measurable against the step
                # itself (opt-in for debugging runs).
                metrics["grad_norm"] = optax.global_norm(grads)
            return new_state, metrics

        donate_args = (0,) if donate else ()
        self._step = jax.jit(train_step, donate_argnums=donate_args)
        self._eval_loss = jax.jit(
            lambda state, batch: model.loss_fn(
                state.params, batch, jax.random.key(0)
            )[0]
        )
        #: AOT-compiled train step (``warm_step``); when present,
        #: ``step()`` calls it directly.  On this jax the jit dispatch
        #: cache is NOT warmed by ``.lower().compile()`` — the first
        #: real call recompiles from scratch — so holding the compiled
        #: executable is the only way a pre-warm actually removes the
        #: cold compile from the first post-resize step.
        self._compiled_step = None
        #: serializes state_shardings' init compile across the resize
        #: window's concurrent threads (restore vs the AOT warmer)
        self._shardings_lock = threading.Lock()

    # -- shardings ----------------------------------------------------------
    def state_shardings(self) -> Any:
        """Per-leaf sharding pytree for TrainState on this mesh.

        Replicated for pure-DP models; for models with partition rules
        the layout is whatever GSPMD propagated from the param
        constraints — derived here by *compiling* init (no execution,
        no throwaway allocation: this runs inside the resize window).
        Locked: the resize window computes this from two threads at
        once (restore placement and the AOT step warmer) — one pays the
        init compile, the other reuses it."""
        if self._param_spec_fn is None:
            return NamedSharding(self.mesh, P())
        with self._shardings_lock:
            if self._state_shardings is None:
                with self.mesh:
                    compiled = (
                        jax.jit(self._init_fn)
                        .lower(jax.random.key(self.seed))
                        .compile()
                    )
                self._state_shardings = compiled.output_shardings
            return self._state_shardings

    def abstract_state(self) -> Any:
        """TrainState as shape/dtype structs — the shared schema every
        allocation-free path derives from (AOT warming, the restore
        transfer's leaf template, cold-start treedefs)."""
        return jax.eval_shape(self._init_fn, jax.random.key(self.seed))

    # -- AOT warming --------------------------------------------------------
    def warm_step(self, abstract_batch) -> bool:
        """AOT-compile the train step from ABSTRACT values — zero
        device allocation however many world sizes are warmed — and
        keep the executable for ``step()``.

        ``abstract_batch``: ShapeDtypeStructs carrying the batch's
        shapes/dtypes/shardings (``ShardedDataIterator.abstract_batch``).
        The state side comes from ``abstract_state()`` with this mesh's
        state shardings attached, so the lowered program's layout is
        identical to what a real call would produce.  Returns True when
        a compile happened, False when the step was already warm.
        Idempotent and safe to call from a background thread during
        steady-state steps (the prewarm path)."""
        if self._compiled_step is not None:
            return False
        t0 = time.perf_counter()
        shardings = self.state_shardings()
        abstract = self.abstract_state()
        if isinstance(shardings, NamedSharding):
            uniform = shardings
            shardings = jax.tree_util.tree_map(lambda _: uniform, abstract)
        abs_state = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract,
            shardings,
        )
        with self.mesh:
            compiled = self._step.lower(abs_state, abstract_batch).compile()
        self._compiled_step = compiled
        # Telemetry: the AOT warm's cost lands in the registry so the
        # "resize windows perform zero compiles" claim has its measured
        # counterpart (where the compile time actually went).
        telemetry.get_registry().histogram("edl_compile_seconds").observe(
            time.perf_counter() - t0
        )
        return True

    @property
    def step_warm(self) -> bool:
        """Whether the train step holds a pre-built executable (the
        warm-resize accounting the zero-compile tests assert on)."""
        return self._compiled_step is not None

    def init_state(self) -> TrainState:
        """Initialize state directly on the mesh: params laid out by the
        model's partition rules (replicated when there are none)."""
        rng = jax.random.key(self.seed)
        with self.mesh:
            if self._param_spec_fn is None:
                init = jax.jit(
                    self._init_fn, out_shardings=NamedSharding(self.mesh, P())
                )
            else:
                init = jax.jit(self._init_fn)  # constraints inside init_fn
            state = init(rng)
        self._state_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, state
        )
        return state

    # -- stepping -----------------------------------------------------------
    def step(self, state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
        with self.mesh:
            if self._compiled_step is not None:
                # The AOT-warmed executable: identical program, but the
                # first call pays ZERO JIT (the jit path would recompile
                # even after lower().compile() — see warm_step).  Input
                # avals/shardings match by construction: the abstract
                # lowering used this mesh's state shardings and the
                # iterator's batch spec, so any mismatch here is a real
                # schema bug that must surface, not be retried.
                return self._compiled_step(state, batch)
            return self._step(state, batch)

    def eval_loss(self, state: TrainState, batch) -> jax.Array:
        with self.mesh:
            return self._eval_loss(state, batch)

    def lower_step(self, state, batch):
        """AOT lowering hook (HLO inspection / ad-hoc compiles).  NOTE:
        the returned executable is NOT installed for ``step()`` and the
        jit dispatch cache is NOT warmed by it — use ``warm_step`` to
        actually remove the first-step JIT from a resize window."""
        return self._step.lower(state, batch).compile()

    @property
    def world_size(self) -> int:
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        return sizes.get(AXIS_DP, 1) * sizes.get(AXIS_FSDP, 1)
