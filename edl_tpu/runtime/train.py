"""The pjit data-parallel train step — the pserver replacement.

In the reference system a training step's gradient sync crossed process
boundaries: trainer -> pserver TCP push/pull, with pserver count pinned
at job submission (``PADDLE_INIT_NUM_GRADIENT_SERVERS`` fixed to
MinInstance, ``pkg/jobparser.go:298`` — sync SGD wasn't even
elastic-aware, SURVEY.md §7.4).  Here the whole step — forward,
backward, gradient allreduce over ICI, optimizer update — is ONE
XLA-compiled program over a ``jax.sharding.Mesh``: batch sharded on the
``dp`` axis, params replicated (or sharded via the model's partition
rules), XLA inserting the collectives.  Elasticity = constructing a new
``Trainer`` over a different-size mesh and restoring state onto it
(see ``edl_tpu.runtime.elastic``).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.models.base import ModelDef
from edl_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP


@struct.dataclass
class TrainState:
    """Minimal train state pytree: step counter, params, optimizer state.

    Deliberately not flax's TrainState: checkpoint/restore (with
    resharding) wants a plain pytree with no bound apply_fn."""

    step: jax.Array
    params: Any
    opt_state: Any


class Trainer:
    """Compiles and runs the train step for one (model, optimizer, mesh).

    One Trainer == one world-size generation.  On resize, the elastic
    runtime builds a fresh Trainer over the new mesh and moves state
    into it via the checkpoint store.
    """

    def __init__(
        self,
        model: ModelDef,
        optimizer: optax.GradientTransformation,
        mesh: Mesh,
        seed: int = 0,
        donate: bool = True,
        metrics_grad_norm: bool = False,
    ):
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.seed = seed
        self.metrics_grad_norm = metrics_grad_norm
        self._base_rng = jax.random.key(seed)

        # Parameter shardings: model partition rules if provided, else
        # fully replicated (pure DP).
        self._param_spec_fn = model.param_partition
        self._state_shardings = None  # cached after init_state()

        axis_names = set(mesh.axis_names)

        def filter_spec(spec: P) -> P:
            """Drop references to axes this mesh doesn't have, so one
            rule set serves every mesh (a pure-DP mesh simply ignores
            tp/fsdp placements)."""

            def keep(entry):
                if entry is None:
                    return None
                if isinstance(entry, (tuple, list)):
                    kept = tuple(a for a in entry if a in axis_names)
                    return kept if kept else None
                return entry if entry in axis_names else None

            return P(*(keep(e) for e in spec))

        def constrain(params):
            """Pin params to the model's partition rules on this mesh;
            XLA's sharding propagation then lays out grads/opt-state to
            match (GSPMD does the work the reference's pserver sharding
            did by hand)."""
            if self._param_spec_fn is None:
                return params
            specs = self._param_spec_fn(params)
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, filter_spec(s)),
                specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.lax.with_sharding_constraint(params, shardings)

        self._constrain = constrain

        def init_fn(rng):
            params = constrain(model.init_params(rng))
            opt_state = optimizer.init(params)
            return TrainState(
                step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state
            )

        self._init_fn = init_fn

        def train_step(state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
            step_rng = jax.random.fold_in(self._base_rng, state.step)

            def loss_of(p):
                loss, aux = model.loss_fn(p, batch, step_rng)
                return loss, aux

            (loss, aux), grads = jax.value_and_grad(loss_of, has_aux=True)(
                state.params
            )
            updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
            new_params = constrain(optax.apply_updates(state.params, updates))
            new_state = TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            )
            metrics = dict(aux)
            metrics["loss"] = loss
            if self.metrics_grad_norm:
                # Off by default: a tree-wide norm is ~260 small
                # reductions per step — measurable against the step
                # itself (opt-in for debugging runs).
                metrics["grad_norm"] = optax.global_norm(grads)
            return new_state, metrics

        donate_args = (0,) if donate else ()
        self._step = jax.jit(train_step, donate_argnums=donate_args)
        self._eval_loss = jax.jit(
            lambda state, batch: model.loss_fn(
                state.params, batch, jax.random.key(0)
            )[0]
        )

    # -- shardings ----------------------------------------------------------
    def state_shardings(self) -> Any:
        """Per-leaf sharding pytree for TrainState on this mesh.

        Replicated for pure-DP models; for models with partition rules
        the layout is whatever GSPMD propagated from the param
        constraints — derived here by *compiling* init (no execution,
        no throwaway allocation: this runs inside the resize window)."""
        if self._param_spec_fn is None:
            return NamedSharding(self.mesh, P())
        if self._state_shardings is None:
            with self.mesh:
                compiled = (
                    jax.jit(self._init_fn)
                    .lower(jax.random.key(self.seed))
                    .compile()
                )
            self._state_shardings = compiled.output_shardings
        return self._state_shardings

    def init_state(self) -> TrainState:
        """Initialize state directly on the mesh: params laid out by the
        model's partition rules (replicated when there are none)."""
        rng = jax.random.key(self.seed)
        with self.mesh:
            if self._param_spec_fn is None:
                init = jax.jit(
                    self._init_fn, out_shardings=NamedSharding(self.mesh, P())
                )
            else:
                init = jax.jit(self._init_fn)  # constraints inside init_fn
            state = init(rng)
        self._state_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, state
        )
        return state

    # -- stepping -----------------------------------------------------------
    def step(self, state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
        with self.mesh:
            return self._step(state, batch)

    def eval_loss(self, state: TrainState, batch) -> jax.Array:
        with self.mesh:
            return self._eval_loss(state, batch)

    def lower_step(self, state, batch):
        """AOT lowering hook: pre-compile the step for this mesh size so a
        resize pays no JIT cost on its first step (<60s resize budget,
        BASELINE.md)."""
        return self._step.lower(state, batch).compile()

    @property
    def world_size(self) -> int:
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        return sizes.get(AXIS_DP, 1) * sizes.get(AXIS_FSDP, 1)
