"""The elastic training loop: re-mesh, restore, resume.

This is the capability the whole reference system exists to enable but
never itself implements (SURVEY.md §3.3: "everything downstream of the
[Parallelism] PUT is delegated").  The contract EDL imposed on its
external runtime — "I will add and remove trainer pods at any time; you
must tolerate membership churn" — is discharged here natively:

1. Between steps, the trainer compares its generation with the
   coordinator's plan (poll — the analog of watching etcd).
2. On a generation change it runs the **resize barrier**:
   a. graceful resize: finish the in-flight step, synchronously flush a
      fresh checkpoint to host DRAM (no lost steps);
      failure recovery: skip the flush (state is gone), fall back to
      the last async checkpoint and *replay* — deterministic data
      (``runtime/data.py``) makes the replay bit-identical.
   b. rebuild the device mesh at the new world size,
   c. restore state onto the new mesh (resharding in ``checkpoint``),
   d. ack the generation and resume stepping.
3. Every ``checkpoint_interval`` steps it snapshots asynchronously —
   the always-warm restore source that keeps resizes under the 60s
   north-star budget (BASELINE.md).

Compiled-step reuse: Trainers are cached per world size, so returning
to a previously seen size pays zero recompilation — and
``precompile()`` can warm every legal world size up front
(SURVEY.md §7.4 "pre-compile per legal mesh size").  Warming is
ABSTRACT (``Trainer.warm_step`` lowers from ``jax.eval_shape`` values,
zero device allocation) and holds the compiled executable — on current
jax ``.lower().compile()`` does not warm the jit dispatch cache, so
holding it is what actually removes the first-step JIT.  The resize
window itself overlaps everything that can overlap: the flush's crc
hash + durable spill run on a background thread (only the d2h copy is
ordered before teardown), the new size's step compile runs parallel to
restore/transfer, and the autoscaler's prewarm hint
(``ElasticPlan.prewarm``) warms the incoming size BEFORE the retarget
even lands — a fully warm resize performs zero XLA compiles.

Steady state is a bounded async pipeline (``pipeline_depth``, default
2): a background stager builds batches for the next steps while the
device computes, step metrics stay device futures harvested with a lag,
and the host tracks the step counter itself — the per-step
host<->device round trips (batch staging, ``int(state.step)``,
``float(loss)``) are off the critical path.  The blocking sync happens
only at the sanctioned sync points (harvest lag, checkpoint interval,
resize-barrier entry, hold, run exit; ``tools/lint.py`` rejects any
other blocking fetch in ``run``), and since the global batch is a pure
function of ``(seed, step)``, the loss stream is bit-identical with the
pipeline on or off — including across resizes and replays.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
import optax

from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.checkpoint.hostdram import HostCheckpoint
from edl_tpu.consensus import (
    BusPoisonError,
    CollectiveWatchdog,
    StepBus,
    timing_bucket,
)
from edl_tpu.models.base import ModelDef
from edl_tpu.parallel.mesh import MeshSpec, build_mesh
from edl_tpu.runtime.coordinator import ElasticPlan, LocalCoordinator
from edl_tpu.runtime.data import ShardedDataIterator
from edl_tpu.runtime.train import Trainer, TrainState

#: Mesh axes the global batch shards over (dp x fsdp; tp/sp/ep/pp
#: replicate the batch) — must agree with
#: ``resource.training_job.BATCH_LAYOUT_AXES``.
BATCH_AXES = ("dp", "fsdp")


class FatalWorldError(RuntimeError):
    """Unrecoverable world-management failure (e.g. the launcher's
    dead-world leak budget is exhausted): the process must exit loudly
    so the pod restarts and rejoins — holding and retrying would only
    repeat the failure.  ``_rebuild_world`` re-raises this where
    ordinary formation errors degrade to hold-and-retry."""


@dataclass
class ResizeEvent:
    generation: int
    world_size: int
    seconds: float
    restored_step: int
    replayed_steps: int
    graceful: bool
    #: how this process got its state: "init" (fresh), "local" (own
    #: store, no cross-pod traffic), "broadcast" (this member moved
    #: state over the restore-transfer wire — as source or receiver —
    #: because some member lacked the agreed bytes)
    restore_source: str = ""
    #: per-phase breakdown of ``seconds`` (flush / world_formation /
    #: remesh / restore) so a resize-latency regression is
    #: attributable to ONE phase instead of a single opaque number
    #: (the r4->r5 resize_max 0.33->0.80s jump was unattributable)
    phase_seconds: Dict[str, float] = None
    #: streaming restore-transfer accounting (multi-process resizes):
    #: bytes this member sent/received and the leaves it skipped
    #: because its local bytes already matched the source
    transfer: Optional[Dict[str, Any]] = None
    #: the stop step this resize honored: the data-plane-agreed boundary
    #: every member left the old world at; -1 when the resize was
    #: immediate (no live multi-member world to agree with — the
    #: coordinator's advisory stamp lives in its own journal)
    stop_step: int = -1
    #: TRUE XLA compiles inside the resize barrier (backend_compile
    #: seam delta; persistent-cache hits don't count).  -1 = the seam
    #: wasn't instrumented (``EDL_COUNT_XLA_COMPILES`` off) — only a
    #: counted 0 is evidence of a zero-compile warm resize.  The count
    #: through the FIRST post-resize step lands on the ``step.first``
    #: flight event (the dispatch of that step is where a failed warm
    #: would pay its compile).
    xla_compiles: int = -1


@dataclass
class StepRecord:
    step: int
    generation: int
    world_size: int
    loss: float
    #: lag-corrected wall seconds attributed to this step.  With the
    #: async pipeline, a step's record is finalized when its device
    #: metrics are HARVESTED (possibly ``pipeline_depth`` steps later):
    #: ``seconds`` is completion-to-completion time against the
    #: previous harvested step (first step of a generation: completion
    #: minus its own dispatch), so steady-state values measure device
    #: throughput, not host dispatch latency.  With the pipeline off
    #: (depth 0) this reduces to the old stage+step+sync measure.
    seconds: float


@dataclass
class _InFlightStep:
    """A dispatched-but-unharvested step: everything needed to finalize
    its StepRecord once the device metrics resolve."""

    step: int
    generation: int
    world_size: int
    t_dispatch: float
    metrics: Dict[str, Any] = field(repr=False, default=None)
    #: the step's gathered control word (edl_tpu.consensus.StepBus) —
    #: a device future harvested with the same lag as the metrics
    bus_word: Any = field(repr=False, default=None)


class ElasticTrainer:
    """Single-host elastic runtime driving the whole world.

    In production each host runs one of these over its slice of the
    processes; in local/test mode it drives all ``world_size`` simulated
    trainers at once (one device == one trainer replica), which
    exercises the identical re-mesh/restore path.
    """

    def __init__(
        self,
        model: ModelDef,
        optimizer: optax.GradientTransformation,
        data: ShardedDataIterator,
        coordinator: LocalCoordinator,
        store: Optional[HostDRAMStore] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        devices_per_trainer: int = 1,
        checkpoint_interval: int = 50,
        seed: int = 0,
        world_builder: Optional[Callable[[Any], Sequence[jax.Device]]] = None,
        layout: Optional[Dict[str, int]] = None,
    ):
        """``model``: a ModelDef, or (for deployed parallelism layouts)
        a ``mesh -> ModelDef`` factory from ``models.base.bind_model``
        — sp/ep/pp families close over the mesh, so each generation's
        re-mesh must rebuild the model too.

        ``layout``: model-axis sizes (fsdp/tp/sp/ep/pp) from the job's
        ``ParallelismSpec.axes()``.  Each generation's mesh is then
        ``dp x <layout>`` with dp absorbing the elastic world size —
        the coordinator's legal sizes guarantee divisibility
        (``TrainingJob.legal_world_sizes``).  None/empty = the pure-dp
        mesh (the reference's one strategy).

        ``world_builder``: optional hook invoked with each new
        ElasticPlan to (re)build the *process group* and return the
        global device list for the new generation.  Single-process runs
        leave it None (devices never change).  The deployed multi-pod
        launcher passes one that tears down and re-initializes
        ``jax.distributed`` from the plan's rank-ordered addresses —
        cross-pod gradient sync requires all member processes in one
        JAX world (XLA collectives cannot span separate worlds).  When
        set, the compiled-trainer cache is invalidated on every
        generation (device objects change identity across re-inits),
        and a plan that does not include any of this process's
        ``heartbeat_ids`` puts it in *standby*: world torn down, polling
        until a future plan readmits it."""
        if isinstance(model, ModelDef):
            self.model = model
            self._model_factory = None
        else:
            # mesh -> ModelDef factory (deployed layouts); bind a
            # mesh-free instance now so pre-mesh consumers
            # (synth data shape, param_partition presence) work.
            self._model_factory = model
            self.model = model(None)
        self.layout = {
            a: int(s) for a, s in (layout or {}).items() if int(s) > 1
        }
        self.optimizer = optimizer
        self.data = data
        self.coordinator = coordinator
        self.store = store if store is not None else HostDRAMStore()
        if devices is not None:
            self.devices = list(devices)
        elif world_builder is not None:
            # Multi-pod: querying devices now would initialize the
            # backend before jax.distributed can form the world; the
            # builder supplies devices at first resize.
            self.devices = []
        else:
            self.devices = jax.devices()
        self.devices_per_trainer = devices_per_trainer
        self.checkpoint_interval = checkpoint_interval
        self.seed = seed
        self.world_builder = world_builder

        self.generation = -1
        self._standby = False
        #: pod ids of the generation whose state we currently hold (the
        #: collective-flush safety gate reads it, see _can_flush)
        self._world_members: tuple = ()
        self.mesh = None
        self.state: Optional[TrainState] = None
        self._trainers: Dict[int, Trainer] = {}  # world_size -> compiled Trainer
        #: guards the trainer cache against the background AOT prewarm
        #: thread; the epoch counter invalidates in-flight warms when a
        #: resize clears the cache (device identity changed)
        self._trainer_lock = threading.Lock()
        self._cache_epoch = 0
        #: in-flight background warms, world_size -> thread
        self._prewarm_threads: Dict[int, threading.Thread] = {}
        #: sizes whose background warm failed this cache epoch — a
        #: deterministically unwarmable hint (e.g. batch not divisible
        #: at that size) must not respawn a compile thread + traceback
        #: every steady-state step; cleared with the trainer cache
        self._failed_prewarms: set = set()
        #: autoscaler prewarm hints dropped by chaos (test accounting)
        self._dropped_prewarm_hints = 0
        self._last_completed_step = 0
        self._holding = False
        #: steady-state pipeline: max in-flight (dispatched, metrics
        #: unharvested) steps.  2 = one step computing while the next
        #: stages + dispatches; 0 = the legacy synchronous loop (one
        #: host<->device round trip per step) — the bench A/B mode.
        #: Donation already permits run-ahead (the jit consumes each
        #: state exactly once); the cap keeps the resize barrier's
        #: drain bounded and deterministic.
        self.pipeline_depth: int = 2
        #: host-side step counter (the device ``state.step`` fetch that
        #: used to block every iteration is retired); synced from
        #: ``restored_step`` at every resize, advanced at dispatch.
        self._host_step = 0
        #: dispatched steps whose device metrics are still in flight,
        #: oldest first — drained at the sanctioned sync points
        #: (harvest lag, checkpoint interval, resize-barrier entry,
        #: hold, run exit)
        self._pending: deque = deque()
        self._stager = None
        self._on_step: Optional[Callable[[StepRecord], None]] = None
        self._last_harvest_t: Optional[float] = None
        #: step attribution for a failure surfaced at harvest time (a
        #: poisoned collective raises when the lagged metrics sync, not
        #: when the step dispatched — replay/max_world_failures need
        #: the step that actually failed)
        self._harvest_failed_step: Optional[int] = None
        #: set by maybe_resize when a barrier is due but in-flight
        #: steps must drain first (run() drains and re-polls)
        self._defer_for_drain = False
        #: cumulative per-phase hot-loop accounting (bench A/B reads
        #: the deltas): host batch staging, jit dispatch, harvest-time
        #: device wait, and the deepest in-flight queue observed
        self.pipeline_stats: Dict[str, float] = {
            "stage_s": 0.0,
            "dispatch_s": 0.0,
            "device_wait_s": 0.0,
            "max_in_flight": 0,
        }
        #: how long run() waits for a formable world before giving up
        self.barrier_timeout: float = 300.0
        self.barrier_poll_interval: float = 0.05
        #: streaming restore-transfer tuning (checkpoint/transfer.py):
        #: chunk granularity for the pipelined TCP transfer and how
        #: long either side waits on a silent peer before abandoning
        #: the transfer to the normal broken-world machinery
        self.transfer_chunk_bytes: int = 64 << 20
        self.transfer_timeout: float = 120.0
        #: sharded peer-to-peer checkpoint fabric (checkpoint/fabric.py):
        #: multiprocess restores agree at SHARD granularity and a
        #: joiner pulls from many peers in parallel, falling back
        #: per-shard to replica holders and wholesale to the PR 2
        #: single-source stream when the world offers no multi-peer
        #: coverage.  EDL_FABRIC=0 pins every restore to the stream.
        import os as _os

        from edl_tpu.checkpoint.fabric import deployment_shard_bytes

        self.fabric_enabled: bool = _os.environ.get("EDL_FABRIC", "1") != "0"
        self.fabric_replicas: int = int(_os.environ.get("EDL_FABRIC_K", "1"))
        #: one definition of the deployment's shard granularity —
        #: spill manifests derive boundaries from the same knob, so
        #: their digest vectors stay cache-key-compatible
        self.fabric_shard_bytes: int = deployment_shard_bytes()
        self.fabric_max_streams: int = 8
        #: shard-only host residency (EDL_SHARD_ONLY=1): each member
        #: keeps its own GSPMD slice + K ring-buddy shards resident in
        #: the fabric's replica store instead of full host checkpoints
        #: — per-member host DRAM is (1+K)/world of state, so aggregate
        #: cluster memory (not one host) caps model size.  Flushes trim
        #: to shards after K buddies ack; spills write only owned
        #: shards; cold starts seed residency from the shard-spill
        #: union.  Requires the fabric (the resident store IS the
        #: fabric's serving source).
        self.shard_only: bool = (
            self.fabric_enabled
            and _os.environ.get("EDL_SHARD_ONLY", "0") == "1"
        )
        if self.shard_only:
            self.store.shard_only = True
        #: persistent shard endpoint + buddy-replica store, created on
        #: the first multiprocess restore (never in local/test runs)
        self._fabric_server = None
        self._fabric_replica_store = None
        #: rank -> (ip, port) fabric addresses cached from the last
        #: shard agreement — what stage-B replication and the victim's
        #: inheritance push dial without another gather
        self._fabric_peer_addrs: Dict[int, tuple] = {}
        self._fabric_rank: int = -1
        self._fabric_world: int = 0
        #: last stage-B replication thread (tests join it)
        self._fabric_replication = None
        #: member ids this process keeps alive at the coordinator (the
        #: launcher sets its own pod id; local mode sets all simulated
        #: members).  Heartbeats are what make eviction-based failure
        #: detection live (SURVEY.md §5.3).
        self.heartbeat_ids: List[str] = []
        #: this process's reachable address, re-sent when an evicted
        #: member rejoins (a rejoin without it would poison the plan's
        #: rank-ordered addresses for every member)
        self.register_address: str = ""
        #: multi-host slice placement, re-sent on rejoin for the same
        #: reason (eviction erased it at the coordinator)
        self.register_replica: Optional[int] = None
        self.register_host: Optional[int] = None
        self._leaving = False
        self.heartbeat_interval: float = 2.0
        self._last_heartbeat = 0.0
        self._hb_thread = None
        self._hb_stop = None
        #: set when the live process group broke mid-step (ungraceful
        #: peer death): hold until the coordinator bumps the generation
        self._await_new_generation = False
        #: consecutive broken-world recoveries with no progress PAST the
        #: failing step: above this the error is deterministic, not
        #: membership churn
        self.max_world_failures: int = 3
        self._world_failures = 0
        #: the step being attempted when the world last broke — the
        #: failure cap resets only when the step counter advances PAST
        #: it (a replayed interval re-completing earlier steps must not
        #: re-arm an unbounded teardown/replay loop pinned at one step,
        #: ADVICE r3)
        self._last_failed_step = -1

        self.resize_events: List[ResizeEvent] = []
        self.history: List[StepRecord] = []
        #: optional observer called with each ResizeEvent (the launcher
        #: logs them to the history file for observability/tests)
        self.on_resize: Optional[Callable[[ResizeEvent], None]] = None

        # Opt-in device tracing (EDL_PROFILE_DIR; SURVEY.md §5.1 —
        # the reference had no tracing at all).
        from edl_tpu.utils.profiling import StepProfiler

        self.profiler = StepProfiler()

        # Default-on telemetry: the process-global registry + flight
        # recorder (edl_tpu.telemetry; tests swap them via scoped()).
        # Handles are resolved once so the hot loop pays only the
        # handle's own lock — bench.py measures the realized per-step
        # cost against the median step time (< 1% acceptance bar).
        from edl_tpu import telemetry

        self.telemetry = telemetry.get_registry()
        self.recorder = telemetry.get_recorder()
        # Compile accounting: moves only when the backend_compile seam
        # is instrumented (bench.py's ad-hoc patch or the launcher's
        # EDL_COUNT_XLA_COMPILES); the env flag additionally journals
        # each resize window's delta so REAL-process tests can assert
        # zero-compile warm resizes from worker journals.
        import os as _os

        self._m_xla = self.telemetry.counter("edl_xla_compiles_total")
        self._count_compiles = (
            _os.environ.get("EDL_COUNT_XLA_COMPILES", "0") == "1"
        )
        self._compiles_at_resize = 0.0
        self._m_steps = self.telemetry.counter("edl_steps_total")
        self._m_step_seconds = self.telemetry.histogram("edl_step_seconds")
        self._m_resizes = self.telemetry.counter("edl_resizes_total")
        self._m_resize_seconds = self.telemetry.histogram(
            "edl_resize_seconds"
        )
        self._m_resize_phase = self.telemetry.histogram(
            "edl_resize_phase_seconds"
        )
        self._m_replayed = self.telemetry.counter(
            "edl_replayed_steps_total"
        )
        self._m_world_breaks = self.telemetry.counter(
            "edl_world_breaks_total"
        )
        self._m_reports = self.telemetry.counter(
            "edl_telemetry_reports_total"
        )
        self._m_pipeline_depth = self.telemetry.gauge("edl_pipeline_depth")
        self._m_device_wait = self.telemetry.histogram(
            "edl_device_wait_seconds"
        )
        #: how often (seconds) the merged-telemetry report piggybacks
        #: on the heartbeat cadence; 0 disables reporting
        self.telemetry_interval: float = 5.0
        self._last_telemetry_report = 0.0
        self._telemetry_seq = 0
        self._events_sent_seq = 0
        # Per-process nonce: lets the aggregator tell a RESTARTED
        # trainer (fresh seq stream) from a stale replay of the old
        # incarnation's high-seq reports.
        import uuid as _uuid

        self._telemetry_boot = _uuid.uuid4().hex[:12]
        self._m_clock_offset = self.telemetry.gauge(
            "edl_clock_offset_seconds"
        )

        # Goodput ledger: the honest wall-clock decomposition
        # (stepping / staging_stalled / resizing[:phase] / holding /
        # replaying / broken) behind edl_goodput_* — fed at the loop's
        # existing transition points, aggregated job-wide by the
        # coordinator, read back by the autoscaler's decision log.
        from edl_tpu.telemetry.ledger import GoodputLedger

        self.ledger = GoodputLedger(registry=self.telemetry)
        #: steps below this replay work already completed before a
        #: non-graceful fallback (the ledger's "replaying" bound)
        self._replay_until = 0

        # Causal tracing (edl_tpu.telemetry.trace): the plan's trace id
        # is installed as the recorder's ambient trace for the whole
        # resize and cleared after the first post-resize step journals
        # (step.first) — one id from autoscaler decision to first step.
        self._first_step_trace_gen: Optional[int] = None
        #: the trace id step.first must close (captured at resize time
        #: — the AMBIENT trace may already belong to a NEWER plan when
        #: a pending step harvests inside the next barrier's drain)
        self._first_step_trace = ""
        #: (hint, trace) pairs already journaled (prewarm.hint dedup)
        self._hint_journaled: set = set()

        # -- data-plane step agreement (edl_tpu.consensus) ------------------
        #: dispatch the per-step int32 control word (the "step bus") on
        #: multi-member worlds — generation/stop/health/timing lanes
        #: allgathered over the SAME collectives as the model step
        self.consensus_bus: bool = True
        #: defer teardown at a retarget to the bus-agreed stop step
        #: (``stop_step = vote_step + pipeline_depth + 1``) so every
        #: member leaves the old world at the SAME step boundary.  None
        #: = auto: engaged under a world_builder (multipod — the only
        #: place the poll-skew teardown race can deadlock a gloo
        #: collective against a shutdown barrier); local single-process
        #: worlds resize immediately as before.  NOTE the horizon is
        #: derived from ``pipeline_depth``, which must agree across
        #: members (same deployment env) — like every other world-wide
        #: config knob.
        self.consensus_stop: Optional[bool] = None
        #: collective-watchdog deadline on harvest-time device fetches
        #: (a wedged gloo allreduce has no native timeout); None = auto:
        #: 120s under a world_builder, disabled single-process
        self.collective_timeout: Optional[float] = None
        self._bus = StepBus(registry=self.telemetry, recorder=self.recorder)
        self._watchdog: Optional[CollectiveWatchdog] = None
        self._m_quiesce = self.telemetry.histogram(
            "edl_consensus_quiesce_seconds"
        )
        #: stop-agreement state, reset at resize/standby/world-break
        self._stop_gen = 0  # generation the pending quiesce is for
        self._stop_agreed: Optional[int] = None
        self._vote_cast_gen = 0
        #: highest plan generation learned from a PEER via the word's
        #: generation lane (a delayed poll still clamps run-ahead)
        self._bus_seen_gen = 0
        #: set to mark this member's outgoing words poisoned (peers
        #: bury the world instead of discovering the failure as a hang)
        self._bus_poison = False
        self._last_step_bucket = 0
        self._quiesce_t0: Optional[float] = None
        self._quiesce_deadline: Optional[float] = None
        self._quiesce_recorded = False
        #: chaos[consensus.vote.delayed]: plan polls suppressed until
        #: this monotonic deadline (simulated poll skew)
        self._poll_suppress_until = 0.0

    # -- trainer cache ------------------------------------------------------
    def _mesh_spec(self, total_devices: int) -> MeshSpec:
        """dp x <layout> mesh shape for a world spanning
        ``total_devices``: the model axes are fixed by the layout, dp is
        the elastic remainder."""
        prod = 1
        for s in self.layout.values():
            prod *= s
        if total_devices % prod != 0:
            raise RuntimeError(
                f"world of {total_devices} devices does not factor into "
                f"parallelism layout {self.layout} (product {prod}); the "
                "coordinator's legal sizes must quantize on the layout "
                "(TrainingJob.legal_world_sizes)"
            )
        return MeshSpec.create(dp=total_devices // prod, **self.layout)

    def _build_trainer(self, world_size: int) -> Trainer:
        """Construct (but do not activate) a Trainer for ``world_size``.
        Cheap: mesh construction + lazy jit wrappers, no compilation."""
        total = world_size * self.devices_per_trainer
        mesh = build_mesh(self._mesh_spec(total), self.devices)
        model = (
            self._model_factory(mesh)
            if self._model_factory is not None
            else self.model
        )
        return Trainer(model, self.optimizer, mesh, seed=self.seed)

    def _trainer_for(self, world_size: int) -> Trainer:
        with self._trainer_lock:
            tr = self._trainers.get(world_size)
        if tr is None:
            built = self._build_trainer(world_size)
            with self._trainer_lock:
                # A background prewarm may have landed the same size
                # while we built: its (possibly already-warm) trainer
                # wins.
                tr = self._trainers.setdefault(world_size, built)
        # Keep self.model pointing at the ACTIVE mesh's instance (the
        # restore paths read its param_partition / init behavior).
        self.model = tr.model
        return tr

    def _clear_trainers(self) -> None:
        """Invalidate the compiled-trainer cache.  Bumping the epoch
        makes any in-flight background warm drop its result instead of
        resurrecting a trainer built over dead device objects."""
        # Staged batches die with the trainers: join the stager's
        # in-flight device_put first so it can't race a backend
        # teardown (the callers about to bury a world).  getattr:
        # tests drive this on __new__-constructed trainers.
        stager = getattr(self, "_stager", None)
        if stager is not None:
            stager.invalidate(join=True)
        # The step bus's per-mesh bindings hold executables over the
        # same dying device objects; drop them with the trainers.
        bus = getattr(self, "_bus", None)
        if bus is not None:
            bus.clear()
        with self._trainer_lock:
            self._trainers.clear()
            self._failed_prewarms.clear()
            self._cache_epoch += 1

    def _warm_trainer(self, tr: Trainer) -> bool:
        """AOT-compile ``tr``'s train step from abstract shapes (see
        ``Trainer.warm_step``): zero device allocation, so warming N
        legal world sizes costs N compiles and nothing else.  Also
        warms the restore path's per-leaf CPU staging conversions
        (mesh-independent, deduped per process) so a first restore
        performs zero compiles inside the resize window too."""
        from edl_tpu.checkpoint.hostdram import warm_leaf_conversions

        warmed = tr.warm_step(
            self.data.abstract_batch(tr.mesh, batch_axes=BATCH_AXES)
        )
        warm_leaf_conversions(
            jax.tree_util.tree_leaves(tr.abstract_state())
        )
        # The step bus's gather compiles per mesh too: warming it here
        # keeps "a warm resize performs zero XLA compiles" true with
        # the consensus lane on (its first dispatch is otherwise inside
        # the first post-resize step's measured window).
        if self.consensus_bus and (
            tr.mesh.devices.size // max(1, self.devices_per_trainer) > 1
        ):
            self._bus.warm(tr.mesh)
        return warmed

    def precompile(self, world_sizes: Sequence[int]):
        """Warm the compiled-step cache for every legal world size
        (avoids JIT cost inside the resize window).  Lowers from
        ABSTRACT shapes — the old path allocated a full real
        ``init_state()`` on device per size just to lower, paying one
        state's worth of HBM per legal world size for nothing."""
        for w in world_sizes:
            self._warm_trainer(self._trainer_for(w))

    # -- background prewarm (the autoscaler hint's consumer) ----------------
    def prewarm_async(self, world_size: int) -> Optional[threading.Thread]:
        """Warm ``world_size``'s step executable on a background thread
        during steady-state stepping, so the NEXT resize finds it
        compiled.  Deduped (an in-flight or already-warm size is a
        no-op); the result is dropped if a resize invalidates the
        trainer cache mid-compile (epoch check).  Returns the warm
        thread (or None if there was nothing to do) so callers/tests
        can join it."""
        with self._trainer_lock:
            if world_size in self._failed_prewarms:
                # Already failed this epoch: deterministic (an illegal
                # size stays illegal until the world changes) — don't
                # respawn a doomed compile thread every step.
                return None
            tr = self._trainers.get(world_size)
            if tr is not None and tr.step_warm:
                return None
            th = self._prewarm_threads.get(world_size)
            if th is not None and th.is_alive():
                return th
            epoch = self._cache_epoch

        def work():
            try:
                target = tr if tr is not None else self._build_trainer(
                    world_size
                )
                self._warm_trainer(target)
                with self._trainer_lock:
                    if self._cache_epoch == epoch:
                        self._trainers.setdefault(world_size, target)
            except Exception:
                # Best-effort: an illegal/unwarmable size must not kill
                # the trainer — the resize path compiles cold instead.
                # Memoized so the steady-state hint consumer doesn't
                # retry (and re-traceback) it once per step.
                with self._trainer_lock:
                    if self._cache_epoch == epoch:
                        self._failed_prewarms.add(world_size)
                import traceback

                traceback.print_exc()

        th = threading.Thread(
            target=work, daemon=True, name=f"edl-prewarm-{world_size}"
        )
        with self._trainer_lock:
            self._prewarm_threads[world_size] = th
        th.start()
        return th

    def _join_prewarm(self, world_size: int) -> None:
        """A resize racing an in-flight prewarm of the SAME size joins
        it: the thread is compiling exactly what the resize needs, and
        racing a duplicate compile would pay twice."""
        with self._trainer_lock:
            th = self._prewarm_threads.get(world_size)
        if th is not None and th.is_alive():
            th.join()

    def _maybe_prewarm(self, plan: ElasticPlan) -> None:
        """Steady-state consumer of the autoscaler's prewarm hint: warm
        exactly the announced incoming world size before the retarget
        lands.  Skipped under a world_builder (device objects change
        identity every generation, so a pre-built executable could
        never be reused — there, the persistent XLA compilation cache
        carries the warming across generations instead)."""
        hint = int(getattr(plan, "prewarm", 0) or 0)
        if not hint or self.world_builder is not None:
            return
        if self.mesh is not None and hint == self._world_size():
            return
        chaos = getattr(self.store, "chaos", None)
        if chaos is not None and chaos.due("prewarm.hint.dropped"):
            # chaos[prewarm.hint.dropped]: the hint is lost en route —
            # the resize must still work, just with a cold compile
            # (overlapped with restore, so the window degrades
            # gracefully rather than stalling).
            self._dropped_prewarm_hints += 1
            return
        if self.prewarm_async(hint) is not None:
            # Journal the warm-ahead under the decision that asked for
            # it (the hint carries the upcoming actuation's trace id),
            # once per (hint, trace): the merged timeline then shows
            # the compile STARTING before the retarget even lands —
            # the zero-stall-resize overlap, visible.
            hint_trace = getattr(plan, "prewarm_trace", "")
            key = (hint, hint_trace)
            if key not in self._hint_journaled:
                self._hint_journaled.add(key)
                self.recorder.record(
                    "prewarm.hint",
                    {"world_size": hint},
                    generation=self.generation,
                    trace=hint_trace,
                )

    # -- fault injection (what the reference never had; SURVEY.md §5.3) -----
    def inject_failure(self):
        """Simulate losing the world's device state mid-run (e.g. a host
        dies).  The next resize must fall back to the last *async*
        checkpoint and replay.  Run-ahead dies with the host: in-flight
        pipelined steps are discarded (never harvested into history),
        so the replay accounting is identical with the pipeline on or
        off — a dead host cannot have confirmed steps it only
        dispatched."""
        self.state = None
        self._pending.clear()
        self._reset_stop_state()
        if self._stager is not None:
            self._stager.invalidate()

    # -- resize barrier -----------------------------------------------------
    def _flush_begin(self, generation: int):
        """Start the split graceful flush: the device->host copy runs
        HERE (it must precede world teardown — the device buffers die
        with the old process group); crc fingerprint + disk spill run
        on the returned background thread, overlapping world formation
        / compile / restore.  Returns (checkpoint, bg_thread_or_None);
        the caller joins the thread before the resize returns."""
        on_bg = None
        if self.fabric_enabled and jax.process_count() > 1:
            # Fabric stage B rides the flush's background thread:
            # shard-digest prewarm inline (it overlaps the window and
            # the next agreement reads it cached), buddy replication
            # on its own daemon (the window's tail join must not wait
            # on peer TCP).  The world/rank/peer snapshot is taken
            # HERE, on the resize thread, while they still describe
            # the world this flush belongs to — the background thread
            # outlives the teardown and would otherwise read the NEW
            # world's values mid-restore and mis-replicate the one
            # flush the shrink's inheritance path depends on.
            world = self._fabric_world
            rank = self._fabric_rank
            peer_addrs = dict(self._fabric_peer_addrs)

            def on_bg(ckpt, _w=world, _r=rank, _p=peer_addrs):
                self._fabric_stage_b(ckpt, world=_w, rank=_r, peers=_p)
        ckpt, bg = self.store.flush_sync(
            self.state, generation=generation, on_background=on_bg
        )
        self.coordinator.report_checkpoint(int(ckpt.step))
        return ckpt, bg

    def _flush(self, generation: int) -> None:
        """Fully synchronous flush (standby / non-resize callers):
        begin + join, surfacing background hash/spill errors like the
        old monolithic flush did."""
        _, bg = self._flush_begin(generation)
        if bg is not None:
            bg.join()
            err = getattr(bg, "edl_error", None)
            if err is not None:
                raise err

    def _can_flush(self, plan: ElasticPlan) -> bool:
        """Whether the live state can be flushed at this resize.

        Collective-free cases (always safe): every leaf is locally
        addressable, fully replicated, or covered by its addressable
        shards (state sharded only over intra-pod axes — the multi-chip
        pod layouts; ``hostdram._cover_regions``).

        Truly cross-pod-sharded state (e.g. fsdp spanning pods) needs an
        allgather over the OLD world, which completes only if every
        old-world member is alive to dispatch it — a departed member
        would hang the survivors mid-flush.  ``plan.alive`` (all live
        registrations, active + standby) is the gate: coordinated
        retargets flush gracefully; an eviction-driven resize degrades
        to the last interval checkpoint + deterministic replay."""
        from edl_tpu.checkpoint.hostdram import _cover_regions

        local = all(
            (not isinstance(l, jax.Array))
            or l.is_fully_addressable
            or l.is_fully_replicated
            or _cover_regions(l) is not None
            for l in jax.tree_util.tree_leaves(self.state)
        )
        if local:
            return True
        return bool(self._world_members) and set(self._world_members) <= set(
            plan.alive
        )

    def _my_member_ids(self, plan: ElasticPlan) -> List[str]:
        """The plan members this process is responsible for.  The
        launcher owns exactly its pod id; local/simulated mode (no
        heartbeat_ids) drives every member."""
        if self.heartbeat_ids:
            mine = [t for t in plan.members if t in self.heartbeat_ids]
            return mine
        return list(plan.members)

    def _rebuild_world(self, plan: ElasticPlan) -> bool:
        """Invoke the world_builder for ``plan``.  Returns False when
        world formation failed (caller holds and retries on the next,
        possibly fresher, plan).

        On success, ``devices_per_trainer`` is re-derived from the
        actual formed world: a trainer replica owns a whole TPU slice
        (ref trainer spec ``pkg/resource/training_job.go:128-134``), so
        a world of ``world_size`` pods with ``c`` chips each must mesh
        over all ``world_size * c`` global devices — not the first
        ``world_size`` (which would exclude every pod but rank 0's
        chips whenever pods carry more than one device)."""
        self._clear_trainers()
        self.mesh = None
        try:
            devs = self.world_builder(plan)
        except FatalWorldError:
            raise  # loud exit, not hold-and-retry (see the class doc)
        except Exception:
            # Hold-and-retry is right for transient races (peers on a
            # fresher plan), but swallowing the traceback entirely made
            # a DETERMINISTIC builder failure (e.g. an initialize()
            # kwarg this jax doesn't know) look like an endless silent
            # hold.  Print once per generation — the retry loop may
            # re-enter many times a second.
            if getattr(self, "_last_form_err_gen", None) != plan.generation:
                self._last_form_err_gen = plan.generation
                import traceback

                traceback.print_exc()
            return False
        if devs is None:
            return False
        if len(devs) % plan.world_size != 0:
            import sys

            print(
                f"[edl] world of {plan.world_size} pods formed with "
                f"{len(devs)} devices (not divisible): heterogeneous "
                "pod device counts are unsupported; holding",
                file=sys.stderr,
            )
            return False
        self.devices = list(devs)
        self.devices_per_trainer = len(devs) // plan.world_size
        return True

    def _enter_standby(self, plan: ElasticPlan) -> None:
        """This process is not in ``plan``'s world: flush what we have,
        tear down our slice of the old world, hold until readmitted.
        When a stop agreement ran (scale-down victims quiesce at the
        agreed boundary like every other member), its latency is
        journaled on the way out."""
        self.ledger.transition("holding")
        self._finish_quiesce()
        self._reset_stop_state()
        if self.state is not None and self._can_flush(plan):
            try:
                self._flush(plan.generation)
            except Exception:
                # Same degradation as _resize's flush guard: a peer
                # death between plan emission and this flush poisons
                # the collective — fall back to the last interval
                # checkpoint + replay rather than dying on the way to
                # standby (the pod must survive to be readmitted).
                import traceback

                traceback.print_exc()
        if self.fabric_enabled and self._fabric_peer_addrs:
            # Fabric stretch: offer the shard inheritance to the
            # surviving ring before parking (offer/accept — when the
            # survivors flushed the same step, nothing moves).  Rides
            # a daemon with a bounded join: an unreachable survivor's
            # connect timeout (up to 30s, serial per buddy) must not
            # stall parking past the scaler's victim-drain window, or
            # the drain ack it is waiting on arrives late and the
            # victim gets SIGTERMed mid-quiesce — the exact failure
            # the ack exists to prevent.  Push uses only TCP + host
            # memory, so it safely outlives the teardown below.
            th = threading.Thread(
                target=self._fabric_push_inheritance,
                daemon=True,
                name="edl-fabric-inherit",
            )
            th.start()
            th.join(timeout=10.0)
        self.state = None
        self._world_members = ()
        self._clear_trainers()
        self.mesh = None
        if self.world_builder is not None:
            try:
                self.world_builder(plan)  # teardown-only (not a member)
            except FatalWorldError:
                raise  # loud exit (leak budget), not silent standby
            except Exception:
                pass
        self.generation = plan.generation
        self._standby = True
        # A standby member's chain ends here (it takes no first step):
        # stop charging steady-state standby events to the resize.
        self.recorder.set_trace("")

    def _finish_overlap(
        self,
        warm_th: Optional[threading.Thread],
        warm_stats: Dict[str, float],
        flush_bg: Optional[threading.Thread],
        phases: Dict[str, float],
    ) -> None:
        """Join the resize window's overlapped background work — the
        AOT step warm and the flush's hash/spill — and record both
        sides of the overlap: ``compile``/``flush_bg`` are the threads'
        own durations, ``*_join`` the residual the window actually
        waited at the end.  join << duration is the proof the work
        overlapped instead of serializing."""
        if warm_th is not None:
            t = time.perf_counter()
            warm_th.join()
            phases["compile_join"] = round(time.perf_counter() - t, 6)
            phases["compile"] = round(warm_stats.get("seconds", 0.0), 6)
        if flush_bg is not None:
            t = time.perf_counter()
            flush_bg.join()
            phases["flush_bg_join"] = round(time.perf_counter() - t, 6)
            phases["flush_bg"] = round(
                getattr(flush_bg, "edl_seconds", 0.0), 6
            )
            err = getattr(flush_bg, "edl_error", None)
            if err is not None:
                # Hash/spill failure AFTER the host copy landed: the
                # DRAM checkpoint is warm and already restored from —
                # no steps lost, durability alone degraded.  Loudly
                # logged, never re-raised into a later resize (the
                # stale-error class of ADVICE r5).
                import sys
                import traceback

                print(
                    "[edl] background flush hash/spill failed (DRAM "
                    f"checkpoint intact; durable spill skipped): {err}",
                    file=sys.stderr,
                )
                traceback.print_exception(
                    type(err), err, err.__traceback__
                )

    def _resize(self, plan: ElasticPlan) -> bool:
        from functools import partial

        from edl_tpu.telemetry import span as _span

        # span() = the utils.profiling trace annotation AND the
        # edl_span_seconds{span=...} histogram under ONE name, so a
        # phase seen in a device trace is searchable on /metrics.
        annotate = partial(_span, registry=self.telemetry)

        self.ledger.transition("resizing")
        t0 = time.perf_counter()
        self._compiles_at_resize = self._m_xla.value()
        phases: Dict[str, float] = {}

        def _mark(name: str, since: float) -> float:
            now = time.perf_counter()
            phases[name] = round(now - since, 6)
            return now

        # The boundary this resize honored: the data-plane agreement
        # when one ran; -1 for an immediate resize (no live
        # multi-member world to agree with — the coordinator's
        # advisory stamp stays in ITS journal, not here: recording it
        # as "honored" would fabricate a boundary that never existed).
        stop_step = self._effective_stop()
        if stop_step is None:
            stop_step = -1
        # Quiesce ends HERE (drained, about to leave the old world):
        # the latency histogram measures retarget->quiesce, not the
        # whole resize window.
        self._finish_quiesce()

        graceful = self.state is not None and self._can_flush(plan)

        flushed: Optional[HostCheckpoint] = None
        flush_bg: Optional[threading.Thread] = None
        if graceful:
            # Flush a fresh checkpoint so no steps are lost.  Only the
            # device-to-host copy is ordered before world teardown (the
            # state's device buffers die with the old process group);
            # crc hashing and the durable spill continue on flush_bg,
            # overlapping everything below, joined before this returns.
            with annotate("resize/flush"):
                try:
                    flushed, flush_bg = self._flush_begin(plan.generation)
                except Exception:
                    # State poisoned by a peer death between the last
                    # step and this resize: degrade to the non-graceful
                    # path (last interval checkpoint + replay).
                    import traceback

                    traceback.print_exc()
                    graceful = False
                    flushed = None
                    flush_bg = None
        t_phase = _mark("flush", t0)

        if self.world_builder is not None:
            self.state = None
            with annotate("resize/world_formation"):
                if not self._rebuild_world(plan):
                    self._finish_overlap(None, {}, flush_bg, phases)
                    return False
            t_phase = _mark("world_formation", t_phase)

        with annotate("resize/remesh"):
            # An in-flight background prewarm of this very size is
            # compiling exactly what we need: join it rather than
            # racing a duplicate compile.
            self._join_prewarm(plan.world_size)
            trainer = self._trainer_for(plan.world_size)
            self.mesh = trainer.mesh
            # Surface batch/mesh mismatch HERE, outside the step loop's
            # broken-world guard: a global batch the mesh can't shard
            # is a configuration error (legal-size metadata disagreeing
            # with chips-per-trainer), not peer churn.
            try:
                self.data.validate_mesh(trainer.mesh, batch_axes=BATCH_AXES)
            except ValueError as e:
                self._finish_overlap(None, {}, flush_bg, phases)
                raise RuntimeError(
                    f"resize to world {plan.world_size} "
                    f"(x {self.devices_per_trainer} chips/trainer) is "
                    f"unsatisfiable: {e}; the coordinator's legal sizes "
                    "must quantize on world x chips "
                    "(TrainingJob.legal_world_sizes)"
                ) from None

        t_phase = _mark("remesh", t_phase)

        # AOT step warm on a parallel thread: the cold-compile cost
        # (when the size was not prewarmed and the persistent cache is
        # cold) overlaps the restore below instead of extending the
        # window.  Already-warm trainers return instantly.
        warm_stats: Dict[str, float] = {}

        def _warm():
            w0 = time.perf_counter()
            try:
                self._warm_trainer(trainer)
            except Exception:
                # Best-effort: a failed warm only means the first step
                # pays the JIT, exactly the pre-warmer behavior.
                import traceback

                traceback.print_exc()
            finally:
                warm_stats["seconds"] = time.perf_counter() - w0

        warm_th = threading.Thread(
            target=_warm, daemon=True, name="edl-resize-warm"
        )
        import os as _os
        if _os.environ.get("EDL_NO_WARM_OVERLAP") == "1" and jax.process_count() > 1:
            # Debug hatch: serialize the warm before the restore phase
            # (isolates overlap-related instability in multi-process
            # worlds).  Phase accounting still records the compile —
            # inline, its whole duration IS window time, so join = 0.
            _warm()
            warm_th = None
            phases["compile"] = round(warm_stats.get("seconds", 0.0), 6)
            phases["compile_join"] = 0.0
        else:
            warm_th.start()

        # Only a FRESHLY materialized flush (its background hash/spill
        # thread exists) may restore without the latest_verified() crc
        # pass: those bytes left the device microseconds ago.  A flush
        # that DEDUPED against an already-stored interval checkpoint
        # (flush_bg is None) has sat in DRAM since the save landed —
        # it keeps the stored-snapshot verify discipline, exactly the
        # pre-split behavior (chaos[checkpoint.corrupt] targets it).
        flushed_fresh = flushed if flush_bg is not None else None

        transfer_stats = None
        with annotate("resize/restore"):
            if jax.process_count() > 1:
                from edl_tpu.checkpoint.transfer import TransferError

                try:
                    self.state, restored_step, restore_source, transfer_stats = (
                        self._restore_multiprocess(
                            trainer, flushed=flushed_fresh
                        )
                    )
                except TransferError:
                    # Torn transfer: world-consistent verdict (every
                    # member raises together via the confirmation
                    # gather) — fail THIS resize attempt, hold, retry;
                    # the fresh agreement re-verifies the source's
                    # bytes, so a wire flip re-transfers and real
                    # source corruption moves the whole world to the
                    # next-oldest verified snapshot together.
                    # Transport faults (source died/stalled before or
                    # during the pull): same hold — the coordinator
                    # evicts the dead peer, bumps the generation, and
                    # the retried agreement elects a live source.
                    # Dying here instead would turn routine peer churn
                    # into receiver-process deaths.
                    import traceback

                    traceback.print_exc()
                    self._finish_overlap(warm_th, warm_stats, flush_bg, phases)
                    return False
            else:
                # The just-flushed checkpoint restores as-is: its bytes
                # were materialized from the device microseconds ago,
                # so the latest_verified() crc pass would re-hash state
                # with no window to have rotted — pure critical-path
                # cost (one of the two r5 hash passes the resize window
                # silently grew).  Dedup'd flushes go through
                # _latest_or_disk's verify instead (see flushed_fresh).
                if self.shard_only:
                    # A single-process world is a 1-member ring: rank 0
                    # owns every shard.  Bind residency HERE (the
                    # multiprocess bind never runs) so flushes/saves at
                    # this world still trim to shards and spill the
                    # per-rank shard family — a later grown world (or a
                    # cold restart) reads one durable format, and a
                    # full-copy spill never leaks out of a shard-only
                    # deployment.
                    if self._fabric_replica_store is None:
                        from edl_tpu.checkpoint.fabric import (
                            ShardReplicaStore,
                        )

                        self._fabric_replica_store = ShardReplicaStore(
                            keep_steps=2
                        )
                    self.store.bind_fabric(
                        0,
                        1,
                        k=self.fabric_replicas,
                        shard_bytes=self.fabric_shard_bytes,
                        resident=self._fabric_replica_store,
                    )
                ckpt = (
                    flushed_fresh
                    if flushed_fresh is not None
                    else self._latest_or_disk(trainer)
                )
                if ckpt is None:
                    # Fresh job: initialize on the new mesh.
                    self.state = trainer.init_state()
                    restored_step = 0
                    restore_source = "init"
                else:
                    # Model-sharded states restore onto this mesh's
                    # actual layout (the re-sharding moment of SURVEY.md
                    # §7.4); pure-DP states replicate.
                    shardings = (
                        trainer.state_shardings()
                        if self.model.param_partition is not None
                        else None
                    )
                    self.state = self.store.restore(
                        ckpt, trainer.mesh, shardings
                    )
                    restored_step = int(ckpt.step)
                    restore_source = "local"
        t_phase = _mark("restore", t_phase)
        self._finish_overlap(warm_th, warm_stats, flush_bg, phases)
        replayed = max(0, self._last_completed_step - restored_step)

        # Re-seed the pipeline's host-side counters for the new
        # generation: stepping resumes at the restored step, the first
        # post-resize StepRecord times against its own dispatch, and
        # nothing staged for the old mesh survives (generation-keyed).
        self._host_step = restored_step
        self._last_harvest_t = None
        # Goodput: refine the just-attributed resize bucket into its
        # measured serial phases, and bound the replay stretch the
        # loop will attribute until the step counter catches back up.
        self.ledger.split_resize(phases)
        self._replay_until = restored_step + replayed
        # Causal trace: the first post-resize step closes this plan's
        # chain (step.first journals in _harvest_one, then the ambient
        # trace clears).
        self._first_step_trace_gen = plan.generation
        self._first_step_trace = getattr(plan, "trace_id", "")
        # Re-arm the device profiler so a bounded trace window can open
        # around THIS resize's first steps (EDL_PROFILE_EACH_RESIZE).
        self.profiler.note_resize()

        self.generation = plan.generation
        self._standby = False
        self._world_members = tuple(plan.members)
        seconds = time.perf_counter() - t0
        event = ResizeEvent(
            generation=plan.generation,
            world_size=plan.world_size,
            seconds=seconds,
            restored_step=restored_step,
            replayed_steps=replayed,
            graceful=graceful,
            restore_source=restore_source,
            phase_seconds=phases,
            transfer=transfer_stats,
            stop_step=stop_step,
            xla_compiles=(
                int(self._m_xla.value() - self._compiles_at_resize)
                if self._count_compiles
                else -1
            ),
        )
        self.resize_events.append(event)
        # Telemetry: counters/histograms for the merged cluster view,
        # plus a flight-recorder event whose deterministic identity
        # (generation/world/restored/replayed/graceful/source — no
        # timings) lets a chaos soak be reconstructed bit-for-bit.
        self._m_resizes.inc(
            graceful=str(graceful).lower(), source=restore_source
        )
        self._m_resize_seconds.observe(seconds)
        for ph, s in phases.items():
            self._m_resize_phase.observe(s, phase=ph)
        if replayed:
            self._m_replayed.inc(replayed)
        timing = {"seconds": round(seconds, 6), "phases": phases}
        if transfer_stats:
            timing["transfer_seconds"] = transfer_stats.get("seconds")
        self.recorder.record(
            "resize",
            {
                "world_size": plan.world_size,
                "restored_step": restored_step,
                "replayed_steps": replayed,
                "graceful": graceful,
                "restore_source": restore_source,
                "stop_step": stop_step,
            },
            step=self._last_completed_step,
            generation=plan.generation,
            timing=timing,
        )
        if self.on_resize is not None:
            self.on_resize(event)
        # Ack only the members this process owns: via the HTTP
        # coordinator, acking on behalf of peers would release the
        # barrier before the world actually re-formed (ADVICE r1).
        for tid in self._my_member_ids(plan):
            self.coordinator.ack_generation(tid, plan.generation)
        self._reset_stop_state()
        return True

    def _latest_or_disk(self, trainer: Trainer) -> Optional[HostCheckpoint]:
        """Latest DRAM checkpoint, falling back to the durable spill dir
        on a cold start (process restarted: DRAM empty, disk warm).

        This is the restore half of EDL_CHECKPOINT_DIR (VERDICT r4 #2):
        without it a whole-world loss — full slice preemption, node-pool
        upgrade, restart-all — silently restarts training from step 0
        despite durable state existing.  A checkpoint that exists but
        cannot be loaded (wrong model's leaves, truncated bytes) raises
        loudly: re-initializing over it would destroy the very state
        the operator mounted the volume to keep.

        DRAM candidates are CRC-verified against the digest recorded
        at save time (``latest_verified``): a corrupted snapshot is
        detected here — the last moment before it would be placed on
        the new mesh — and the next-oldest snapshot restores instead
        (one extra replay interval, not a poisoned run)."""
        ckpt = self.store.latest_verified()
        if ckpt is not None or not self.store.spill_dir:
            return ckpt
        if self.shard_only and jax.process_count() > 1:
            # Shard-only members never assemble full state from disk:
            # the multiprocess restore seeds the RESIDENT store from
            # the shard-spill union instead (load_shards_from_disk) and
            # enters the agreement as a replica-only holder.  A
            # SINGLE-process world is its own union (rank 0 owns every
            # shard), so it falls through to the full assembly below —
            # returning None here would silently restart at step 0.
            return None
        # treedef template from the model's abstract init: no allocation
        # (this runs inside the resize window).
        template = trainer.abstract_state()
        try:
            ckpt = self.store.load_from_disk(template)
        except FileNotFoundError:
            return None  # fresh job: nothing spilled yet
        import sys

        print(
            f"[edl] cold start: restored step {ckpt.step} from durable "
            f"checkpoint dir {self.store.spill_dir}",
            file=sys.stderr,
        )
        # Replays are measured against the durable step, not 0 — a
        # restarted process has no memory of its pre-crash progress.
        self._last_completed_step = max(self._last_completed_step, ckpt.step)
        return ckpt

    def _transfer_fabric(self):
        """Agreement fabric for the streaming restore transfer.  The
        advertised host is this pod's registered address (the same one
        world formation dials); local/test runs without one are
        single-machine, where loopback is correct."""
        from edl_tpu.checkpoint import transfer

        host = (
            self.register_address.rsplit(":", 1)[0]
            if self.register_address
            else "127.0.0.1"
        )
        return transfer.JaxProcessFabric(advertise_host=host)

    # -- sharded p2p checkpoint fabric (checkpoint/fabric.py) ----------------
    def _ensure_fabric_server(self):
        """Lazily start this member's persistent shard endpoint: pulls
        are served from whatever checkpoint the store holds at the
        requested step, falling back to the buddy-replica store; OFFER
        pushes land in the replica store.  Created only on the
        multiprocess restore path, so local/test trainers never bind a
        socket."""
        from edl_tpu.checkpoint.fabric import (
            FabricServer,
            ReplicaIngest,
            ShardReplicaStore,
        )

        if self._fabric_replica_store is None:
            # Shard-only members keep TWO steps resident: an agreement
            # that degrades to the next-oldest step must still find
            # those shards locally — with keep_steps=1, adopting the
            # newer step would have pruned the very step the degrade
            # falls back to.
            self._fabric_replica_store = ShardReplicaStore(
                keep_steps=2 if self.shard_only else 1
            )
        if self._fabric_server is None:

            def has_bytes(step, leaf, offset, length):
                ck = self.store.get(step)
                return (
                    ck is not None
                    and leaf < len(ck.leaves)
                    and ck.leaves[leaf].nbytes >= offset + length
                )

            def lookup(step, leaf, offset, length):
                ck = self.store.get(step)
                if (
                    ck is not None
                    and leaf < len(ck.leaves)
                    and ck.leaves[leaf].nbytes >= offset + length
                ):
                    from edl_tpu.checkpoint.fabric import byte_view

                    return byte_view(ck.leaves[leaf])[
                        offset : offset + length
                    ]
                return self._fabric_replica_store.get(
                    step, leaf, offset, length
                )

            self._fabric_server = FabricServer(
                lookup,
                ingest=ReplicaIngest(self._fabric_replica_store, has_bytes),
                timeout=self.transfer_timeout,
                chaos=self.store.chaos,
            ).start()
        return self._fabric_server

    def _fabric_layout(self, leaves, world: Optional[int] = None):
        """The deployment's shard table over ``leaves`` (abstract or
        materialized — only shapes/nbytes are read).  Row extents come
        from axis 0, the axis the dp/fsdp GSPMD partitions split, so
        shard boundaries nest inside every world size's slices.
        ``world`` overrides the live ``_fabric_world`` for callers
        holding a snapshot of an older world (flush stage B)."""
        from edl_tpu.checkpoint.fabric import ShardLayout, leaf_rows
        from edl_tpu.checkpoint.transfer import _leaf_sizes

        return ShardLayout.build(
            _leaf_sizes(leaves),
            max(1, self._fabric_world if world is None else world),
            k=self.fabric_replicas,
            shard_bytes=self.fabric_shard_bytes,
            rows=leaf_rows(leaves),
        )

    def _fabric_stage_b(
        self, ckpt, *, world: int, rank: int, peers: Dict[int, tuple]
    ) -> None:
        """Flush stage B (background thread): prewarm the per-shard
        digest vector the next agreement reads, then offer this
        member's owned shards to its K deterministic buddies on a
        separate daemon (offer/accept — a collective flush leaves
        every buddy declining, so the common case moves zero bytes).
        ``world``/``rank``/``peers`` are the caller's snapshot of the
        world the flush belongs to — never read live off self here:
        this thread overlaps the next world's restore, which rebinds
        those fields mid-flight."""
        try:
            # Prewarm on THIS thread (joined before the resize
            # returns): the next agreement reads the shard vector
            # cached, and the replicate daemon's recompute below is a
            # cache hit.
            ckpt.shard_digests(self._fabric_layout(ckpt.leaves, world=world))
        except Exception:
            import traceback

            traceback.print_exc()
            return
        peers = dict(peers)
        peers.pop(rank, None)
        if rank < 0 or not peers:
            return

        def replicate():
            summary = self._fabric_offer_owned(
                ckpt,
                world=world,
                rank=rank,
                peers=peers,
                timeout=self.transfer_timeout,
            )
            self.recorder.record(
                "fabric.replicate",
                summary,
                step=int(ckpt.step),
                generation=int(ckpt.generation),
            )
            under = int(summary.get("underreplicated", 0))
            if under > 0:
                # EDL_FABRIC_K enforcement: an owned shard that did not
                # reach every ring buddy is a replication-contract
                # violation, journaled + counted — not advisory.  The
                # next flush re-offers; until then the operator can see
                # exactly which steps run thin.
                from edl_tpu import telemetry

                telemetry.get_registry().counter(
                    "edl_fabric_underreplicated_total"
                ).inc(under)
                self.recorder.record(
                    "fabric.underreplicated",
                    {
                        "step": int(ckpt.step),
                        "shards": under,
                        "k": self.fabric_replicas,
                        "dropped": summary.get("dropped", 0),
                    },
                    step=int(ckpt.step),
                    generation=int(ckpt.generation),
                )

        th = threading.Thread(
            target=replicate, daemon=True, name="edl-fabric-replicate"
        )
        th.start()
        self._fabric_replication = th
        if self.shard_only:
            # Shard-only flushes COMPLETE only once K buddies ack (or
            # the bounded wait expires and the under-replication is
            # journaled above): the full copy is trimmed right after
            # this hook returns, so "durable and fingerprinted before
            # the next step" now includes the ring holding the shards.
            th.join(self.transfer_timeout)

    def _fabric_offer_owned(
        self,
        ckpt,
        *,
        world: Optional[int],
        rank: int,
        peers: Dict[int, tuple],
        timeout: float,
        generation: Optional[int] = None,
    ) -> dict:
        """Offer ``ckpt``'s owned shards to the K ring buddies — the
        ONE sourcing path (layout, cached shard digests, byte_view
        slices) shared by flush stage B and the standby inheritance
        push, so the offset arithmetic can never diverge between
        them."""
        from edl_tpu.checkpoint import fabric as fab

        layout = self._fabric_layout(ckpt.leaves, world=world)
        digs = ckpt.shard_digests(layout)

        def shard_source(s):
            view = fab.byte_view(ckpt.leaves[s.leaf])
            return view[s.offset : s.offset + s.length], digs[s.index]

        return fab.replicate_to_buddies(
            layout,
            rank,
            int(ckpt.step),
            int(ckpt.generation) if generation is None else generation,
            peers,
            shard_source,
            chunk_bytes=self.transfer_chunk_bytes,
            timeout=timeout,
            chaos=self.store.chaos,
        )

    def _fabric_push_inheritance(self) -> None:
        """Consensus-clean scale-down stretch: before parking, a
        victim offers its newest verified shards — owned AND
        buddy-held — to the surviving ring so planned shrinks keep the
        state K-replicated without a durable-dir round trip.
        Best-effort and bounded: a declined offer (survivors flushed
        the same step, the common graceful case) moves zero bytes."""
        from edl_tpu.checkpoint import fabric as fab

        peers = dict(self._fabric_peer_addrs)
        rank = self._fabric_rank
        peers.pop(rank, None)
        if rank < 0 or not peers:
            return
        # latest(), not latest_verified(): a full re-hash here would
        # eat the bounded parking budget at exactly the state scale
        # the fabric targets, and the buddy-side ShardReplicaStore
        # crc-rejects any shard whose bytes no longer match the
        # offered digest — receiver-side verification covers rot.
        ckpt = self.store.latest()
        rep = self._fabric_replica_store
        if ckpt is None and (rep is None or rep.newest_step() < 0):
            return
        try:
            if ckpt is not None:
                summary = self._fabric_offer_owned(
                    ckpt,
                    world=None,
                    rank=rank,
                    peers=peers,
                    timeout=min(30.0, self.transfer_timeout),
                    generation=self.generation,
                )
            else:
                # Shard-only victim: no full checkpoint exists anywhere
                # on this host — its RESIDENT shards (own + buddy-held)
                # are its whole contribution, re-homed below.
                summary = {
                    "step": rep.newest_step(),
                    "offered": 0,
                    "accepted": 0,
                    "bytes": 0,
                    "peers": [],
                    "dropped": 0,
                    "underreplicated": 0,
                }
            ckpt_step = int(ckpt.step) if ckpt is not None else -1
            if rep is not None and rep.newest_step() > ckpt_step:
                # Buddy-held shards NEWER than our own checkpoint may
                # be the only surviving copy of a degraded-flush step:
                # re-home them downstream under THEIR step.
                step = rep.newest_step()
                items = [
                    (leaf, off, length, crc, rep.get(step, leaf, off, length))
                    for leaf, off, length, crc in rep.shards_at(step)
                ]
                items = [it for it in items if it[4] is not None]
                for buddy in sorted(peers):
                    try:
                        acc, sent = fab.push_shards(
                            peers[buddy], rank, step, self.generation,
                            items, timeout=min(30.0, self.transfer_timeout),
                        )
                        summary["accepted"] += acc
                        summary["bytes"] += sent
                        break
                    except (OSError, fab.TransferError):
                        continue
            self.recorder.record(
                "fabric.inherit",
                summary,
                step=int(summary.get("step", ckpt_step)),
                generation=self.generation,
            )
        except Exception:
            import traceback

            traceback.print_exc()

    def _restore_multiprocess(
        self, trainer: Trainer, flushed: Optional[HostCheckpoint] = None
    ):
        """Agree on one state across the (re-formed) process group and
        move ONLY the bytes some member lacks.

        ``flushed``: the checkpoint this resize just flushed, when the
        resize was graceful — it restores without the
        ``latest_verified`` crc pass (bytes materialized from the
        device moments ago cannot have rotted), keeping the hash work
        on the flush's background thread instead of this window.

        Members all-gather (have, step, digest) plus PER-LEAF digests
        (``checkpoint/transfer.py``).  Identical bytes everywhere — the
        common graceful-resize case — restores locally with zero
        cross-pod traffic (VERDICT r3 weak-1).  Otherwise the
        newest-checkpoint holder streams each receiver's missing
        leaves over chunked TCP: a single fresh joiner pulls only what
        it lacks while every survivor restores locally, received
        leaves go to the device while later chunks are still on the
        wire, and chunk CRCs feed the corruption-fallback machinery —
        a torn transfer degrades to the next-oldest verified snapshot
        (or fails the resize for a stateless joiner) instead of
        poisoning the run.  This retired the monolithic
        ``broadcast_one_to_all`` path (25.5s for 728MB at 2 processes,
        BENCH_r05; ``bench.py`` keeps it measured side by side).
        Runs the agreement all-gather: every member process must call
        this inside the same generation's resize.

        Returns (state, restored_step, restore_source, transfer_stats).
        """
        from edl_tpu.checkpoint import transfer
        from edl_tpu.checkpoint.hostdram import leaf_placer

        # Disk fallback first: after a whole-world restart every member's
        # DRAM is empty but the durable dir is warm — the loaded
        # checkpoint then acts as this member's contribution to the
        # agreement (identical spilled bytes everywhere -> local
        # restore; a lone survivor's disk copy -> transfer source).
        ckpt = flushed if flushed is not None else self._latest_or_disk(trainer)
        shardings = (
            trainer.state_shardings()
            if self.model.param_partition is not None
            else None
        )
        # The model's abstract state is the shared leaf schema: shapes,
        # dtypes, and treedef come from the model, not from any local
        # checkpoint (which may be stale or absent).
        abstract = trainer.abstract_state()
        leaves_abs, treedef = jax.tree_util.tree_flatten(abstract)
        if shardings is None:
            from jax.sharding import NamedSharding, PartitionSpec

            leaf_shardings = [
                NamedSharding(trainer.mesh, PartitionSpec())
            ] * len(leaves_abs)
        else:
            leaf_shardings = jax.tree_util.tree_flatten(shardings)[0]
        place = leaf_placer(trainer.mesh)
        placed: List[Any] = [None] * len(leaves_abs)

        def on_leaf(i: int, arr: np.ndarray) -> None:
            # Per-leaf placement the moment bytes are final: device
            # transfer of leaf i overlaps the network pull of leaf i+1.
            placed[i] = place(
                np.asarray(arr).reshape(leaves_abs[i].shape), leaf_shardings[i]
            )

        # TornTransferError propagates to _resize, which fails this
        # resize attempt on EVERY member (the engine's confirmation
        # all-gather made the verdict world-consistent) and
        # holds-and-retries: the fresh agreement re-runs
        # latest_verified on the source, so persistent source
        # corruption degrades the whole world to the next-oldest
        # snapshot TOGETHER — one member quietly restoring an older
        # step would diverge the step counter across a live world.
        fabric_net = self._transfer_fabric()
        if self.fabric_enabled:
            # Sharded p2p fabric: shard-granular agreement, parallel
            # multi-peer pull, per-shard replica fallback — and a
            # world-deterministic hand-off to the PR 2 single-source
            # stream when there is no multi-peer coverage.
            from edl_tpu.checkpoint import fabric as fab

            self._fabric_rank = fabric_net.rank
            self._fabric_world = fabric_net.world
            rows = fab.leaf_rows(leaves_abs)
            # Ordering: _ensure_fabric_server() CREATES the replica
            # store on first use — resolve it before reading the
            # store attribute, or the first restore passes None.
            server = self._ensure_fabric_server()
            if self.shard_only:
                # (Re)bind the store's shard residency to THIS world's
                # topology: boundaries are world-independent, ownership
                # is not.  Must precede the agreement — flush trimming
                # and the cold-start seed below both read the binding.
                self.store.bind_fabric(
                    fabric_net.rank,
                    fabric_net.world,
                    k=self.fabric_replicas,
                    shard_bytes=self.fabric_shard_bytes,
                    resident=self._fabric_replica_store,
                )
                if (
                    ckpt is None
                    and self._fabric_replica_store.newest_step() < 0
                    and self.store.spill_dir
                ):
                    # Shard-only cold start: seed residency with this
                    # member's wanted ranges from the durable shard
                    # union — it then advertises as a replica-only
                    # holder; no process materializes full state.
                    seeded = self.store.load_shards_from_disk(abstract)
                    if seeded is not None:
                        import sys

                        print(
                            f"[edl] shard-only cold start: seeded "
                            f"{seeded['shards']} resident shard(s) "
                            f"({seeded['bytes']} bytes) at step "
                            f"{seeded['step']} from {self.store.spill_dir}",
                            file=sys.stderr,
                        )
                        self._last_completed_step = max(
                            self._last_completed_step, seeded["step"]
                        )
            result = fab.fabric_restore(
                fabric_net,
                leaves_abs,
                ckpt,
                rows=rows,
                k=self.fabric_replicas,
                shard_bytes=self.fabric_shard_bytes,
                replica_store=self._fabric_replica_store,
                server=server,
                chunk_bytes=self.transfer_chunk_bytes,
                timeout=self.transfer_timeout,
                chaos=self.store.chaos,
                on_leaf=on_leaf,
                max_streams=self.fabric_max_streams,
            )
            if result.peer_addrs is not None:
                # Cache every member's fabric address: the stage-B
                # buddy replication and the victim's inheritance push
                # dial these without another gather.
                self._fabric_peer_addrs = dict(result.peer_addrs)
        else:
            result = transfer.stream_restore(
                fabric_net,
                leaves_abs,
                ckpt,
                chunk_bytes=self.transfer_chunk_bytes,
                timeout=self.transfer_timeout,
                chaos=self.store.chaos,
                on_leaf=on_leaf,
            )

        stats = result.stats
        stats_dict = {
            "mode": stats.mode,
            "source_rank": stats.source_rank,
            "bytes_scheduled": stats.bytes_scheduled,
            "bytes_sent": stats.bytes_sent,
            "bytes_received": stats.bytes_received,
            "leaves_received": stats.leaves_received,
            "leaves_skipped": stats.leaves_skipped,
            "chunks_received": stats.chunks_received,
            "seconds": round(stats.seconds, 4),
        }
        if stats.per_peer is not None:
            stats_dict["per_peer_bytes"] = dict(stats.per_peer)
        if stats.shard_fallbacks:
            stats_dict["shard_fallbacks"] = stats.shard_fallbacks
        if stats.mode == "init":
            # Nobody has state (fresh job): deterministic same-seed
            # init everywhere — nothing to move.
            return trainer.init_state(), 0, "init", stats_dict

        if stats.mode == "local":
            # Identical bytes everywhere: restore locally, no wire.
            if ckpt is None or int(ckpt.step) != stats.step:
                # A partial/replica-only holder assembled its full
                # state from local shards (fabric mode "local" without
                # a matching checkpoint): adopt the assembly so this
                # member is a normal local-restore peer next time.
                ckpt = HostCheckpoint(
                    step=stats.step,
                    generation=self.generation,
                    leaves=result.leaves,
                    treedef=treedef,
                )
                if result.leaf_digests is not None:
                    ckpt.adopt_digests(result.leaf_digests)
                self.store.put(ckpt)
            state = self.store.restore(ckpt, trainer.mesh, shardings)
            if self.shard_only:
                # Back to shard residency the moment the device copy
                # exists: adopt wanted ranges, drop the full leaves.
                self.store.trim_to_shards(int(ckpt.step))
            return state, int(ckpt.step), "local", stats_dict

        # Delta mode: every leaf was placed (local digest-matched ones
        # first, received ones as their last chunk landed) — assemble
        # the state straight from the placed device arrays, no second
        # host materialization.
        state = jax.tree_util.tree_unflatten(treedef, placed)
        if (
            stats.bytes_received
            or ckpt is None
            or int(ckpt.step) != stats.step
        ):
            # Adopt the assembled checkpoint so this process can be a
            # local-restore (or source) member after a future resize.
            # The step check matters even at zero bytes pulled: a
            # replica-only holder can assemble the full state from
            # LOCAL buddy shards in fabric mode (a joiner elsewhere
            # keeps the world off the "local" path), and that assembly
            # may be the only full copy of a degraded-flush step — the
            # inheritance push reads it from the store.
            # Zero-copy: the store keeps the very buffers the wire
            # filled, and the digests come from the source's verified
            # advertisement instead of a fresh hash pass.
            merged = HostCheckpoint(
                step=stats.step,
                generation=self.generation,
                leaves=result.leaves,
                treedef=treedef,
            )
            if result.leaf_digests is not None:
                merged.adopt_digests(result.leaf_digests)
            # A fabric assembly without a full-state authority carries
            # no leaf-digest advertisement: put() fingerprints fresh.
            self.store.put(merged)
        if self.shard_only:
            self.store.trim_to_shards(int(stats.step))
        moved = stats.bytes_received or stats.bytes_sent
        if stats.mode == "fabric":
            source = "fabric" if moved else "local"
        else:
            source = "broadcast" if moved else "local"
        return (state, stats.step, source, stats_dict)

    def _beat_once(self):
        if self._leaving:
            return
        for tid in list(self.heartbeat_ids):
            try:
                try:
                    # Piggyback the last completed step: retarget plans
                    # stamp stop_step from it (no extra round-trip).
                    self.coordinator.heartbeat(
                        tid, step=self._last_completed_step
                    )
                except TypeError:
                    # Pre-consensus coordinator / test double without
                    # the step kwarg: the beat itself must still land.
                    self.coordinator.heartbeat(tid)
            except KeyError:
                if self._leaving:
                    return  # deregistered on purpose; do not resurrect
                # Evicted while actually alive (e.g. a long compile or
                # GC pause outlived the lease): rejoin so the capacity
                # isn't silently lost — the generation bump puts us
                # through the normal resize barrier.
                try:
                    self.coordinator.register(
                        tid,
                        address=self.register_address,
                        replica=self.register_replica,
                        host=self.register_host,
                    )
                except Exception:
                    pass  # coordinator unreachable; retry next beat

    def _heartbeat(self):
        """Keep this process's members alive at the coordinator,
        throttled to ``heartbeat_interval``.  A background thread does
        the same so long resize windows (checkpoint flush + compile)
        can't cause self-eviction."""
        if not self.heartbeat_ids:
            return
        self._ensure_heartbeat_thread()
        now = time.monotonic()
        if now - self._last_heartbeat < self.heartbeat_interval:
            return
        self._last_heartbeat = now
        self._beat_once()

    def _maybe_report_telemetry(self) -> None:
        """Throttled telemetry report.  Runs ONLY on the heartbeat
        background thread: the step loop's poll->dispatch window must
        stay tight — a POST between a member's plan poll and its next
        step dispatch skews the members' resize-barrier entry and can
        wedge a scale-down (one member standing down while a peer's
        already-dispatched collective waits for it forever)."""
        if self.telemetry_interval <= 0:
            return
        now = time.monotonic()
        if now - self._last_telemetry_report < self.telemetry_interval:
            return
        self._last_telemetry_report = now
        self._report_telemetry()

    def _report_telemetry(self) -> None:
        """Ship this process's cumulative registry snapshot + the
        flight-recorder tail to the coordinator, piggybacked on the
        heartbeat cadence.  Cumulative + seq'd = idempotent at the
        aggregator; best-effort — telemetry must never stall a step."""
        rep = getattr(self.coordinator, "report_telemetry", None)
        if rep is None:
            return  # test doubles / pre-telemetry coordinators
        source = self.heartbeat_ids[0] if self.heartbeat_ids else "local"
        # OLDEST unsent first, bounded per report: a burst larger than
        # one report drains across the next cadences in order (the
        # watermark only advances past what was actually shipped).
        events = self.recorder.events_since(self._events_sent_seq)[:64]
        self._telemetry_seq += 1
        # Clock alignment piggyback: the HTTP client's heartbeat-fed
        # offset estimate rides the report so the coordinator can
        # place this member's events on the merged timeline.
        clock = None
        est = getattr(self.coordinator, "clock_estimator", None)
        if est is not None:
            off = est.offset()
            if off is not None:
                clock = {"offset": off, "rtt": est.rtt()}
                self._m_clock_offset.set(off)
        try:
            kwargs = dict(
                snapshot=self.telemetry.snapshot(),
                seq=self._telemetry_seq,
                events=[e.to_dict() for e in events],
                boot=self._telemetry_boot,
            )
            try:
                rep(source, clock=clock, **kwargs)
            except TypeError:
                # pre-tracing coordinator / test double without the
                # clock kwarg: the report itself must still land
                rep(source, **kwargs)
        except Exception:
            return  # unreachable coordinator: next cadence retries
        if events:
            self._events_sent_seq = events[-1].seq
        self._m_reports.inc()

    def _ensure_heartbeat_thread(self):
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        import threading

        self._hb_stop = threading.Event()

        def loop():
            while not self._hb_stop.wait(max(self.heartbeat_interval, 0.05)):
                if self.heartbeat_ids:
                    self._beat_once()
                    self._maybe_report_telemetry()

        self._hb_thread = threading.Thread(
            target=loop, daemon=True, name="edl-heartbeat"
        )
        self._hb_thread.start()

    def _world_broken(self) -> None:
        """The live process group failed mid-step.  Drop every handle to
        it and hold for a fresh generation (see maybe_resize).

        The dead world's distributed handles are graveyarded NOW (via
        the builder's barrier-free ``leak_dead_world``), not at the
        next formation: when no legal world exists (e.g. a cross-pod
        tp layout missing a peer), the hold can last minutes, and a
        still-installed client's error-polling thread will terminate()
        the survivor from C++ (std::bad_cast) once the coordination
        service notices the dead peer's dropped connection — observed
        in the cross-pod tp SIGKILL test.  Burying immediately also
        keeps the next formation's teardown a no-op."""
        # Drain in-flight checkpoint saves first (bounded: a save
        # blocked in a dead peer's collective must not hang recovery —
        # on expiry the thread is leaked like the world's handles):
        # burying clears the backends, and a save thread mid-device_get
        # should not have the buffers die under it.  Errors are
        # expected (the world the save was reading is dead) and must
        # not linger in the store — a LATER healthy flush's wait()
        # would re-raise them and spuriously degrade an unrelated
        # resize to the replay path.
        try:
            self.store.wait(timeout=5.0)
        except Exception:
            pass
        self._leak_dead_world()
        # In-flight step futures died with the world; anything not
        # already salvaged by _absorb_step_failure's drain is gone (the
        # restored checkpoint replays those steps deterministically).
        # getattr: tests drive _world_broken on __new__-constructed
        # trainers that never ran __init__.
        pending = getattr(self, "_pending", None)
        if pending is not None:
            pending.clear()
        if getattr(self, "_bus", None) is not None:
            # A broken world voids any in-flight stop agreement (the
            # peers it was made with are gone); the fresh generation's
            # retarget re-agrees from scratch.
            self._reset_stop_state()
        self.state = None
        self._world_members = ()
        self._clear_trainers()
        self.mesh = None
        self._await_new_generation = True
        self._holding = True
        # Defensive: tests drive _world_broken on __new__-constructed
        # trainers that never ran __init__ (no telemetry handles).
        if getattr(self, "ledger", None) is not None:
            self.ledger.transition("broken")
        if getattr(self, "_m_world_breaks", None) is not None:
            self._m_world_breaks.inc()
            self.recorder.record(
                "world.broken",
                {"failed_step": self._last_failed_step},
                step=self._last_completed_step,
                generation=self.generation,
            )

    def stop_heartbeat(self):
        """Stop beating before deregistering.  Marks the trainer as
        leaving (an in-flight beat must not resurrect the membership)
        and joins the thread so no beat lands after this returns."""
        self._leaving = True
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None and self._hb_thread.is_alive():
            self._hb_thread.join(timeout=10)

    # -- data-plane step agreement (edl_tpu.consensus) ----------------------
    def _agreement_horizon(self) -> int:
        """Steps between a stop vote and the agreed boundary.  depth+1
        guarantees the boundary is past EVERY member's run-ahead
        frontier when the agreement is learned: word k is harvested no
        later than after dispatching step k+depth, so stop = k+depth+1
        is always >= frontier+1 — nobody has dispatched a collective
        the others will not join."""
        return max(0, self.pipeline_depth) + 1

    def _bus_active(self) -> bool:
        return (
            self.consensus_bus
            and self.mesh is not None
            and self._world_size() > 1
        )

    def _consensus_stop_active(self) -> bool:
        """Whether a retarget must quiesce at the bus-agreed boundary
        instead of tearing down on sight of the new plan."""
        if self.state is None or not self._bus_active():
            return False
        on = self.consensus_stop
        if on is None:
            on = self.world_builder is not None
        return bool(on)

    def _watchdog_fetch(self, fn, what: str = "step metrics"):
        """Harvest-time device fetch under the collective watchdog's
        deadline (lazy-built: the chaos schedule and timeout knobs are
        attached after construction)."""
        wd = self._watchdog
        if wd is None:
            timeout = self.collective_timeout
            if timeout is None:
                timeout = 120.0 if self.world_builder is not None else 0.0
            wd = CollectiveWatchdog(
                timeout=timeout,
                chaos=getattr(self.store, "chaos", None),
                registry=self.telemetry,
                recorder=self.recorder,
            )
            self._watchdog = wd
        return wd.fetch(fn, what=what)

    def _dispatch_bus_word(self, step: int):
        """This step's outgoing control word (a device future, no host
        sync).  The stop lane carries this member's vote (first step
        after it observed a retarget) or echoes the agreement."""
        if not self._bus_active():
            return None
        gen_seen = max(self.generation, self._stop_gen, self._bus_seen_gen)
        stop = 0
        if self._stop_agreed is not None:
            stop = self._stop_agreed
        elif self._stop_gen > self.generation:
            stop = step + self._agreement_horizon()
            if self._vote_cast_gen != self._stop_gen:
                self._vote_cast_gen = self._stop_gen
                self._bus.note_vote(step, self._stop_gen, stop)
        return self._bus.dispatch(
            self.mesh,
            step,
            gen_seen,
            stop,
            self._bus_poison,
            self._last_step_bucket,
        )

    def _absorb_bus_word(self, rec: _InFlightStep) -> None:
        """Harvest-time decode of step ``rec.step``'s gathered word.
        Every member decodes the identical matrix in the same step
        order, so the agreement needs no further communication."""
        mat = self._watchdog_fetch(
            lambda: np.asarray(rec.bus_word), what="control word"
        )
        word = self._bus.decode(self.mesh, rec.step, mat)
        if word.max_generation > max(self.generation, self._bus_seen_gen):
            # A peer saw a plan generation we have not polled yet: a
            # resize is wanted — the run-ahead clamp holds even while
            # our own poll is delayed.
            self._bus_seen_gen = word.max_generation
        if word.stop_step and self._stop_agreed is None:
            # FIRST word with a nonzero stop lane IS the agreement (the
            # voter proposed vote_step + horizon in it); later words'
            # larger proposals are ignored by everyone alike.
            self._stop_agreed = word.stop_step
            self._stop_gen = max(self._stop_gen, word.max_generation)
            self._start_quiesce_clock()
            self._bus.note_stop(rec.step, word.stop_step, self._stop_gen)
        if word.poisoned:
            raise BusPoisonError(
                f"a peer marked step {rec.step}'s control word poisoned "
                "(member self-reported failure)"
            )

    def _arm_stop(self, plan: ElasticPlan) -> None:
        """This member observed a retarget on a live multi-member
        world: quiesce via the bus instead of tearing down now."""
        if plan.generation > self._stop_gen:
            self._stop_gen = plan.generation
        self._start_quiesce_clock()

    def _effective_stop(self) -> Optional[int]:
        """The boundary this member quiesces at: the data-plane
        agreement.  Of min(coordinator-stamped, agreed), a stamp below
        the agreement is unsafe to honor (the agreement is the
        EARLIEST step no member has dispatched past — stopping under
        it re-opens the poll-skew deadlock this subsystem closes) and
        a stamp above it never shortens the quiesce, so the min-with-
        floor reduces to the agreement exactly; the stamp's job is the
        journal (``coord.plan`` events, the autoscaler decision log),
        not the boundary."""
        return self._stop_agreed

    def _stop_reached(self) -> bool:
        stop = self._effective_stop()
        return stop is not None and self._host_step >= stop

    def _start_quiesce_clock(self) -> None:
        if self._quiesce_t0 is None:
            self._quiesce_t0 = time.perf_counter()
            self._quiesce_deadline = time.monotonic() + self.barrier_timeout

    def _note_quiesced(self) -> None:
        if self._quiesce_recorded:
            return
        self._quiesce_recorded = True
        self.recorder.record(
            "consensus.quiesce",
            {
                "stop_step": self._effective_stop(),
                "for_generation": self._stop_gen,
            },
            step=self._last_completed_step,
            generation=self.generation,
        )

    def _finish_quiesce(self) -> None:
        """Journal + observe the retarget->quiesce latency (once per
        agreement); called on the way into resize/standby."""
        if self._quiesce_t0 is None:
            return
        self._note_quiesced()
        self._m_quiesce.observe(time.perf_counter() - self._quiesce_t0)
        self._quiesce_t0 = None

    def _reset_stop_state(self) -> None:
        self._stop_gen = 0
        self._stop_agreed = None
        self._vote_cast_gen = 0
        self._bus_seen_gen = 0
        self._quiesce_t0 = None
        self._quiesce_deadline = None
        self._quiesce_recorded = False

    def maybe_resize(self) -> bool:
        self._heartbeat()
        plan = self.coordinator.plan()
        if plan is None or plan.world_size < 1:
            # No formable world (e.g. legal_sizes can't fit the surviving
            # membership).  Hold at the barrier — stepping on the stale
            # mesh would hang real multi-host collectives on the dead
            # member's devices.
            self._holding = plan is not None and plan.generation != self.generation
            return False
        if plan.generation != self.generation:
            # A fresh generation supersedes any broken-world hold.
            self._await_new_generation = False
            # Install the plan's causal-trace id as the recorder's
            # ambient trace: every event this member journals on the
            # way through the resize — vote, quiesce, flush, transfer,
            # restore — now carries the id the autoscaler minted (or
            # the coordinator minted for membership churn).  Cleared
            # when the first post-resize step journals.  Idempotent
            # across the repeated polls of a quiescing member.
            plan_trace = getattr(plan, "trace_id", "")
            if plan_trace:
                self.recorder.set_trace(plan_trace)
        if plan.generation == self.generation and (
            self.state is not None
            or self._standby
            or self._await_new_generation
        ):
            # _await_new_generation: the current generation's process
            # group broke under us (peer died mid-collective).  Re-forming
            # the SAME plan would block on the dead member's address;
            # hold cheaply until the lease reaper evicts it and bumps
            # the generation.
            self._holding = self._standby or self._await_new_generation
            if self.state is not None and not self._holding:
                # Steady state: act on the autoscaler's prewarm hint so
                # the NEXT generation's step executable compiles in the
                # background while this one keeps stepping.
                self._maybe_prewarm(plan)
            return False
        if self._consensus_stop_active():
            # A retarget hit a LIVE multi-member world: leaving on
            # sight of the new plan is the poll-skew race (one member
            # stands down a step boundary before its peer and the
            # peer's dispatched collective waits forever).  Quiesce via
            # the step bus instead: vote, agree on
            # stop_step = vote_step + horizon in-band, and keep
            # stepping to that exact boundary — every member leaves the
            # old world at the SAME step.
            chaos = getattr(self.store, "chaos", None)
            if chaos is not None:
                for ev in chaos.due("consensus.vote.delayed"):
                    # chaos[consensus.vote.delayed]: this member's plan
                    # poll is suppressed — it must keep stepping
                    # obliviously, and the stop must still reach it
                    # in-band (the property the point exists to prove).
                    self._poll_suppress_until = time.monotonic() + float(
                        ev.arg or 1.0
                    )
            if time.monotonic() < self._poll_suppress_until:
                return False
            self._arm_stop(plan)
            if not self._stop_reached():
                return False
        if self._pending:
            # Sanctioned sync point: resize-barrier entry.  In-flight
            # steps must harvest BEFORE the barrier tears anything down
            # (their device futures die with the old world, and their
            # records must land in history ahead of any replay), and
            # the drain must run inside run()'s broken-world guard — a
            # poisoned collective surfaces here, attributed to its
            # step.  run() drains and re-polls a fresh plan.
            self._defer_for_drain = True
            return False
        if self.heartbeat_ids and not self._my_member_ids(plan):
            # Multi-pod scale-down: this pod dropped out of the world's
            # rank order.  Stand by (keep heartbeating) until a future
            # plan readmits it — the analog of the reference's standby
            # pods the kube Job controller folds back in.
            self._enter_standby(plan)
            self._holding = True
            return False
        if not self._resize(plan):
            # World formation failed (e.g. peers raced to a newer plan):
            # hold; the next poll retries against the fresh plan.
            self._holding = True
            return False
        self._holding = False
        return True

    # -- the async step pipeline --------------------------------------------
    def _next_batch(self, step: int, trainer: Trainer, horizon: int):
        """Step ``step``'s device batch: prefetched by the background
        stager when the pipeline is on, built inline when off.  Either
        path yields the identical batch — ``(seed, step) -> indices``
        is pure, so prefetch changes when, never what."""
        if self.pipeline_depth <= 0:
            return self.data.device_batch(
                step, trainer.mesh, batch_axes=BATCH_AXES
            )
        if self._stager is None:
            from edl_tpu.runtime.data import BatchStager

            self._stager = BatchStager(
                self.data,
                depth=self.pipeline_depth,
                batch_axes=BATCH_AXES,
                chaos=getattr(self.store, "chaos", None),
            )
        # Generation-keyed: a resize re-keys the stager, so a batch
        # placed on the pre-resize mesh can never be dispatched.
        self._stager.rebind(trainer.mesh, self.generation)
        return self._stager.get(step, horizon=horizon)

    def _harvest_pending(self, limit: int) -> None:
        """Harvest (oldest first) until at most ``limit`` steps remain
        in flight.  limit=pipeline_depth is the steady-state lag;
        limit=0 is a full drain (the sanctioned sync points)."""
        while len(self._pending) > limit:
            self._harvest_one()

    def _harvest_one(self) -> None:
        """Resolve the oldest in-flight step's device metrics and
        finalize its StepRecord.  The blocking ``float`` lives HERE —
        the sanctioned sync point — not in the dispatch loop; a
        poisoned collective surfacing in it is attributed to this
        step (``_harvest_failed_step``) for the replay machinery."""
        rec = self._pending[0]
        t0 = time.perf_counter()
        try:
            loss = self._watchdog_fetch(lambda: float(rec.metrics["loss"]))
        except Exception:
            self._harvest_failed_step = rec.step
            self._pending.popleft()
            raise
        self._pending.popleft()
        if rec.bus_word is not None:
            try:
                # Decode the step's control word (same sanctioned sync:
                # the gather resolves with the step stream).  A
                # poisoned word or wedged gather is attributed to this
                # step for the broken-world recovery, like the loss.
                self._absorb_bus_word(rec)
            except Exception:
                self._harvest_failed_step = rec.step
                raise
        now = time.perf_counter()
        self._m_device_wait.observe(now - t0)
        self.pipeline_stats["device_wait_s"] += now - t0
        # Lag-corrected timing: completion-to-completion against the
        # previous harvested step (see StepRecord.seconds).
        base = (
            rec.t_dispatch
            if self._last_harvest_t is None
            else max(rec.t_dispatch, self._last_harvest_t)
        )
        self._last_harvest_t = now
        srec = StepRecord(
            step=rec.step,
            generation=rec.generation,
            world_size=rec.world_size,
            loss=loss,
            seconds=now - base,
        )
        # The NEXT outgoing control word carries this step's timing
        # bucket — the free per-member straggler signal.
        self._last_step_bucket = timing_bucket(srec.seconds)
        self.history.append(srec)
        # Default-on per-step telemetry: one counter inc, one histogram
        # observe, one context stamp (measured in bench.py's
        # telemetry_overhead — ~µs against ms steps).
        self.recorder.set_context(rec.step, rec.generation)
        self._m_steps.inc()
        self._m_step_seconds.observe(srec.seconds)
        if self._first_step_trace_gen is not None and (
            rec.generation >= self._first_step_trace_gen
        ):
            # The first harvested step of the fresh generation closes
            # the resize's causal chain — under the trace CAPTURED at
            # resize time, not the ambient one: a pending step
            # harvesting inside the NEXT barrier's drain (back-to-back
            # retargets within the pipeline lag) would otherwise
            # journal under the newer plan's just-installed trace and
            # clear it mid-resize.
            self._first_step_trace_gen = None
            first_data = {"world_size": rec.world_size}
            if self._count_compiles:
                # Barrier entry -> first post-resize step harvested:
                # the whole window the zero-compile warm-resize claim
                # is about, journaled so a REAL-process test reads the
                # count from the member's spill (bench measures the
                # same delta at the same seam in-process).
                first_data["xla_compiles"] = int(
                    self._m_xla.value() - self._compiles_at_resize
                )
            self.recorder.record(
                "step.first",
                first_data,
                step=rec.step,
                generation=rec.generation,
                trace=self._first_step_trace,
            )
            if self.recorder.trace_context() == self._first_step_trace:
                self.recorder.set_trace("")
            self._first_step_trace = ""
        self.ledger.touch()
        if self._on_step is not None:
            self._on_step(srec)
        done_step = rec.step + 1
        self._last_completed_step = max(
            self._last_completed_step, done_step
        )
        if done_step > self._last_failed_step:
            # Progress PAST the last failing step: genuine recovery,
            # re-arm the cap.  Merely replaying the pre-failure
            # interval does not count — a deterministic error recurring
            # at one step (e.g. a poisoned checkpoint path) must
            # exhaust the cap and surface, not loop teardown/replay
            # forever.
            self._world_failures = 0

    def _absorb_step_failure(self, dispatch_step: Optional[int]) -> bool:
        """The broken-world recovery decision, shared by every guarded
        site of the step loop (dispatch, lagged harvest, barrier-entry
        drain).  Must be called from inside an ``except`` block.
        Returns True when the failure was absorbed (world buried, hold
        for a fresh generation) — False means the caller must re-raise
        (deterministic bug / no recovery possible)."""
        # Salvage completed older steps first: a dispatch failure at
        # step k leaves k-1, k-2... in flight, possibly healthy — their
        # records belong in history, and the EARLIEST poisoned step is
        # the honest attribution.  FIFO harvesting stops at the first
        # failure; the rest died with the world.
        if self._harvest_failed_step is None and self._pending:
            try:
                self._harvest_pending(0)
            except Exception:
                pass  # _harvest_failed_step now names the earliest
        attempted = self._harvest_failed_step
        self._harvest_failed_step = None
        if attempted is None:
            attempted = (
                dispatch_step
                if dispatch_step is not None
                else self._last_completed_step
            )
        self._pending.clear()
        if not (
            self.world_builder is not None
            and self.mesh is not None
            and self._world_size() > 1
            and self._world_failures < self.max_world_failures
        ):
            return False
        # A peer died mid-collective (SIGKILL, preemption): the process
        # group is unusable but THIS process is fine.  Survive it: drop
        # the world, await the eviction-bumped generation, resume from
        # the last checkpoint with deterministic replay (SURVEY.md §5.3
        # — the reference delegated exactly this to master/etcd
        # re-registration).  Capped: repeated failures with no
        # completed step in between are a deterministic bug, not churn.
        import traceback

        traceback.print_exc()
        if attempted != self._last_failed_step:
            # A failure at a DIFFERENT step than the previous one is
            # churn (later = progress happened in between; earlier = a
            # fresh strike during the replay window) — re-arm the cap.
            # Only a failure pinned at the same step accumulates toward
            # the deterministic-bug diagnosis.
            self._world_failures = 0
        self._world_failures += 1
        self._last_failed_step = attempted
        self._world_broken()
        return True

    def _drain_guarded(self) -> bool:
        """Full drain under the broken-world guard (the sync points
        outside the dispatch ``try``: barrier entry, hold).  Returns
        False when a failure was absorbed (caller re-polls)."""
        if not self._pending:
            return True
        try:
            self._harvest_pending(0)
        except Exception:
            if self._absorb_step_failure(None):
                return False
            self._leak_dead_world()
            raise
        return True

    # -- the loop -----------------------------------------------------------
    def run(
        self,
        num_steps: int,
        on_step: Optional[Callable[[StepRecord], None]] = None,
    ) -> List[StepRecord]:
        """Run until the global step counter reaches ``num_steps``.

        The step counter survives resizes (re-seeded from the restored
        checkpoint), so ``num_steps`` counts *completed global steps*,
        not loop iterations (replayed steps after a failure re-run the
        same step numbers).

        Steady state is a bounded async pipeline (``pipeline_depth``,
        default 2): batches for the next steps stage on a background
        thread while the device computes, dispatched steps run ahead of
        their metrics, and the blocking device sync happens only at
        harvest (lagged) or at a sanctioned sync point — checkpoint
        interval, resize-barrier entry, hold, run exit.  Depth 0
        restores the synchronous loop.  The loss/metric stream is
        bit-identical either way: batches are a pure function of
        ``(seed, step)`` and harvesting only defers WHEN values are
        read."""
        hold_started: Optional[float] = None
        self._on_step = on_step
        self._m_pipeline_depth.set(self.pipeline_depth)
        try:
            while True:
                self.maybe_resize()
                if self._defer_for_drain:
                    # Sanctioned sync point: resize-barrier entry.
                    self._defer_for_drain = False
                    self._drain_guarded()
                    continue  # re-poll; the drained pipeline resizes
                if self._holding:
                    # A hold after a world break is the BREAK's wait
                    # (recovery hasn't happened yet); an ordinary hold
                    # is just an unformable plan.  touch() keeps the
                    # counters accruing through a LONG park — the
                    # telemetry reports riding the heartbeat cadence
                    # must show the degradation while it is happening,
                    # not after the park ends.
                    self.ledger.transition(
                        "broken"
                        if self._await_new_generation
                        else "holding"
                    )
                    self.ledger.touch()
                    # Sanctioned sync point: hold.  A world with no
                    # formable plan drains its in-flight steps before
                    # parking (their futures must not outlive whatever
                    # teardown ends the hold).
                    if not self._drain_guarded():
                        continue
                    # Barrier hold: the coordinator's current plan has
                    # no formable world.  Poll until membership
                    # recovers (the coordinator bumps the generation
                    # when it does).  Standby is different: a healthy
                    # steady state (the pod waits to be readmitted),
                    # never a timeout.
                    now = time.monotonic()
                    if self._standby:
                        hold_started = None
                    elif hold_started is None:
                        hold_started = now
                    elif now - hold_started > self.barrier_timeout:
                        # BROKEN worlds were already buried by
                        # _world_broken; this covers the un-broken case
                        # (a healthy world whose plan shrank to
                        # unformable): abandon its handles barrier-free
                        # so exit destructors can't mask this
                        # diagnostic.
                        self._leak_dead_world()
                        raise RuntimeError(
                            f"held at resize barrier > "
                            f"{self.barrier_timeout}s with no formable "
                            "world"
                        )
                    time.sleep(self.barrier_poll_interval)
                    continue
                hold_started = None
                if self.state is None:
                    self._leak_dead_world()
                    raise RuntimeError(
                        "no plan with world_size >= 1 available"
                    )
                if self._stop_agreed is not None and self._stop_reached():
                    # Quiesced at the data-plane-agreed stop boundary:
                    # run-ahead is clamped HERE — no member dispatches a
                    # collective past the step every member agreed to
                    # leave at.  Park (drained) until the new plan is
                    # visible to this member too (maybe_resize completes
                    # the resize/standby from the top of the loop); a
                    # chaos-delayed poll sits in this state until the
                    # suppression expires.
                    self.ledger.transition("holding")
                    self.ledger.touch()
                    if not self._drain_guarded():
                        continue
                    self._note_quiesced()
                    if (
                        self._quiesce_deadline is not None
                        and time.monotonic() > self._quiesce_deadline
                    ):
                        self._leak_dead_world()
                        raise RuntimeError(
                            "quiesced at agreed stop step "
                            f"{self._effective_stop()} but no actionable "
                            f"plan arrived within {self.barrier_timeout}s"
                        )
                    time.sleep(self.barrier_poll_interval)
                    continue
                step = None  # the step this iteration attempts
                try:
                    # The whole body is guarded: an async collective
                    # poisoned by a peer's ungraceful death can surface
                    # at ANY device access here (the dispatch itself or
                    # a lagged harvest) — not just inside trainer.step.
                    step = self._host_step
                    if step >= num_steps:
                        # Sanctioned sync point: run exit.  Every
                        # dispatched step confirms before returning.
                        self._harvest_pending(0)
                        break
                    trainer = self._trainers[self._world_size()]
                    self.profiler.maybe_start(step)
                    # Goodput: replayed steps re-earn work a fallback
                    # already completed once — not fresh progress.
                    self.ledger.transition(
                        "replaying"
                        if step < self._replay_until
                        else "stepping"
                    )
                    t0 = time.perf_counter()
                    with self.profiler.step(step):
                        batch = self._next_batch(step, trainer, num_steps)
                        t1 = time.perf_counter()
                        self.state, metrics = trainer.step(
                            self.state, batch
                        )
                    t2 = time.perf_counter()
                    self.pipeline_stats["stage_s"] += t1 - t0
                    self.pipeline_stats["dispatch_s"] += t2 - t1
                    # The host time blocked on batch assembly/placement
                    # is the ledger's staging_stalled carve-out (the
                    # stall the async stager exists to hide).
                    self.ledger.note_staging(t1 - t0)
                    self._pending.append(
                        _InFlightStep(
                            step=step,
                            generation=self.generation,
                            world_size=self._world_size(),
                            t_dispatch=t0,
                            metrics=metrics,
                            # The step's control word rides the same
                            # world as the step itself (a device
                            # future, harvested with the same lag).
                            bus_word=self._dispatch_bus_word(step),
                        )
                    )
                    if self.profiler.tracing:
                        # Sanctioned sync point: a LIVE bounded trace
                        # (tracing, not enabled — enabled stays true
                        # for the whole process and would disable the
                        # pipeline forever).  The trace must capture
                        # THIS step's device work too, which is still
                        # in flight — drain after appending it, before
                        # maybe_stop() can close the trace, or the
                        # tail steps' compute is truncated (the old
                        # loop's per-step sync did this implicitly).
                        self._harvest_pending(0)
                    self.profiler.maybe_stop()
                    self._host_step = step + 1
                    done_step = step + 1
                    if (
                        self.checkpoint_interval > 0
                        and done_step % self.checkpoint_interval == 0
                    ):
                        # Sanctioned sync point: interval checkpoint.
                        # Confirm every step up to done_step before the
                        # snapshot (keeps save/record/on_step ordering
                        # identical to the synchronous loop).
                        self._harvest_pending(0)
                        self.store.save_async(
                            self.state, generation=self.generation
                        )
                        self.coordinator.report_checkpoint(done_step)
                    else:
                        self._harvest_pending(self.pipeline_depth)
                    if len(self._pending) > self.pipeline_stats[
                        "max_in_flight"
                    ]:
                        self.pipeline_stats["max_in_flight"] = len(
                            self._pending
                        )
                except Exception:
                    if self._absorb_step_failure(step):
                        continue
                    # Fatal: no next formation will tear this world
                    # down.  Abandon its handles barrier-free so
                    # interpreter-exit destructors can't hang/abort on
                    # dead peers and mask the diagnostic traceback.
                    self._leak_dead_world()
                    raise
        finally:
            self._on_step = None
        self.profiler.stop()  # close any live trace at target step
        return self.history

    def _leak_dead_world(self) -> None:
        """Best-effort barrier-free abandonment of the current world's
        distributed handles (see launcher.make_world_builder's
        leak_dead_world).  FatalWorldError — the graveyard's leak
        budget — must keep propagating: the broken-world recovery path
        calls this too, and a process that survives 32 ungraceful
        world deaths must exit loudly, not swallow the cap and leak
        clients/ports forever."""
        leak = getattr(self.world_builder, "leak_dead_world", None)
        if leak is not None:
            try:
                leak()
            except FatalWorldError:
                raise
            except Exception:
                pass

    def _world_size(self) -> int:
        # Trainer count = total mesh devices / devices-per-trainer (the
        # mesh may factor devices over dp x fsdp x ..., so no single
        # axis carries the world size).
        return max(1, self.mesh.devices.size // self.devices_per_trainer)
