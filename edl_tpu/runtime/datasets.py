"""File-backed datasets: memory-mapped array stores on disk.

The reference delegated real data entirely to the external runtime:
trainer pods received a user ``Workspace``/``TRAINER_PACKAGE`` and the
fault-tolerant master dispatched data-shard *tasks* via etcd
(``/root/reference/pkg/jobparser.go:288-291``; SURVEY.md §5.3).  Here
data is a first-class, deterministic subsystem: an **array store** is a
directory of ``.npy`` files (one per feature) plus a JSON manifest, and
loading it memory-maps every array so trainers stream real bytes from
disk without materializing the dataset in RAM.  A memmapped store plugs
straight into ``ShardedDataIterator`` — batch assembly fancy-indexes
the maps, so only the touched rows are ever paged in — which preserves
the (seed, step) -> indices determinism the elastic protocol depends
on: a resize re-slices the same global batch stream whether the bytes
live in RAM or on disk.

This is the adapter BASELINE configs use for "real data" training
(MNIST/ImageNet-shaped arrays staged to disk once, then trained from
file); any pipeline that can emit numpy arrays (TFDS, webdataset,
tokenized text) stages into it with ``save_array_store``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

MANIFEST = "manifest.json"


def save_array_store(
    path: str,
    arrays: Dict[str, np.ndarray],
    seed: Optional[int] = None,
    provenance: Optional[Dict[str, str]] = None,
) -> str:
    """Write ``arrays`` (shared leading dim) as ``<key>.npy`` files plus
    a manifest.  Atomic enough for the single-writer staging pattern:
    the manifest is written last, so a crashed half-written store fails
    ``load_array_store`` loudly instead of loading short arrays.

    ``provenance``: optional source metadata recorded in the manifest
    (e.g. the ingester's per-source-file sha256 checksums) so a staged
    corpus is auditable back to its bytes."""
    if not arrays:
        raise ValueError("array store needs at least one array")
    sizes = {k: len(v) for k, v in arrays.items()}
    if len(set(sizes.values())) != 1:
        raise ValueError(f"arrays disagree on leading dim: {sizes}")
    os.makedirs(path, exist_ok=True)
    # Invalidate any existing store FIRST: a crash mid-restage must
    # leave a store that fails load loudly, never an old manifest
    # validating a mix of old and new .npy files.
    try:
        os.remove(os.path.join(path, MANIFEST))
    except FileNotFoundError:
        pass
    meta = {"n": next(iter(sizes.values())), "arrays": {}, "seed": seed}
    if provenance:
        meta["provenance"] = dict(provenance)
    for key, v in arrays.items():
        if "/" in key or key.startswith("."):
            raise ValueError(f"bad array key {key!r}")
        np.save(os.path.join(path, f"{key}.npy"), np.asarray(v))
        meta["arrays"][key] = {
            "shape": list(v.shape),
            "dtype": str(np.asarray(v).dtype),
        }
    tmp = os.path.join(path, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(path, MANIFEST))
    return path


def load_array_store(path: str, mmap: bool = True) -> Dict[str, np.ndarray]:
    """Load a store as a dict of (by default) memory-mapped arrays,
    validated against the manifest — shape/dtype drift between staging
    and training fails here, not as a silent garbage batch."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        raise FileNotFoundError(
            f"{path!r} is not an array store (no {MANIFEST}); stage one "
            "with edl_tpu.runtime.datasets.save_array_store"
        )
    with open(mpath) as f:
        meta = json.load(f)
    out: Dict[str, np.ndarray] = {}
    for key, info in meta["arrays"].items():
        v = np.load(
            os.path.join(path, f"{key}.npy"),
            mmap_mode="r" if mmap else None,
        )
        if list(v.shape) != info["shape"] or str(v.dtype) != info["dtype"]:
            raise ValueError(
                f"array {key!r} drifted from manifest: "
                f"{v.shape}/{v.dtype} != {info['shape']}/{info['dtype']}"
            )
        out[key] = v
    return out


def validate_for_model(dataset: Dict[str, np.ndarray], model) -> None:
    """Fail fast — before any compile — when a store doesn't match the
    batches the model's loss reads (a mismatch otherwise surfaces as a
    bare ``KeyError`` or opaque XLA shape error deep inside the jit'd
    step).  The model's own ``synth_batch`` is the shape/dtype
    contract: per-feature trailing dims and dtype must agree."""
    ref = model.synth_batch(np.random.RandomState(0), 1)
    missing = set(ref) - set(dataset)
    if missing:
        raise ValueError(
            f"array store lacks features {sorted(missing)} required by "
            f"model {model.name!r} (store has {sorted(dataset)})"
        )
    for key, want in ref.items():
        got = dataset[key]
        if got.shape[1:] != want.shape[1:]:
            raise ValueError(
                f"array store feature {key!r} has per-example shape "
                f"{tuple(got.shape[1:])}; model {model.name!r} expects "
                f"{tuple(want.shape[1:])}"
            )
        if np.asarray(got).dtype != np.asarray(want).dtype:
            raise ValueError(
                f"array store feature {key!r} has dtype {got.dtype}; "
                f"model {model.name!r} expects {np.asarray(want).dtype}"
            )


def stage_synthetic(
    path: str, model_synth_batch, n_examples: int, seed: int = 0
) -> str:
    """Stage a model's deterministic synthetic dataset to disk — the
    zero-download stand-in for a real corpus that still exercises the
    full file-backed path (mmap -> fancy-index -> device)."""
    rng = np.random.RandomState(seed)
    return save_array_store(path, model_synth_batch(rng, n_examples), seed=seed)


# -- real-corpus ingestion ---------------------------------------------------

#: IDX dtype codes (the MNIST distribution format,
#: http://yann.lecun.com/exdb/mnist/ — a magic of 0x00 0x00 <dtype>
#: <ndim>, big-endian uint32 dims, then row-major data).
_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (optionally .gz) into a numpy array."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    if len(raw) < 4 or raw[0] != 0 or raw[1] != 0:
        raise ValueError(f"{path!r} is not an IDX file (bad magic)")
    code, ndim = raw[2], raw[3]
    if code not in _IDX_DTYPES:
        raise ValueError(f"{path!r}: unknown IDX dtype code 0x{code:02x}")
    dims = np.frombuffer(raw, ">u4", count=ndim, offset=4)
    dtype = _IDX_DTYPES[code]
    start = 4 + 4 * ndim
    want = int(np.prod(dims)) if ndim else 0
    avail = (len(raw) - start) // np.dtype(dtype).itemsize
    if avail < want:
        # Checked up front: frombuffer's own error names no file.
        raise ValueError(
            f"{path!r}: truncated IDX payload ({avail} of {want} items)"
        )
    data = np.frombuffer(raw, dtype, count=want, offset=start)
    return data.reshape(tuple(int(d) for d in dims))


def _sha256(path: str) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def ingest_mnist_idx(
    out_path: str, images_path: str, labels_path: str
) -> str:
    """Ingest a real MNIST-format corpus (IDX image + label files, the
    BASELINE config-2 dataset) into an array store matching the
    ``mnist`` model's batch contract: ``image`` [N, 28, 28, 1] float32
    in [0, 1], ``label`` [N] int32.  The source files' sha256 checksums
    land in the manifest's provenance block, so a staged store is
    auditable back to the exact bytes it came from (VERDICT r4 #8:
    trained bytes that did not come from ``synth_batch``)."""
    imgs = read_idx(images_path)
    labs = read_idx(labels_path)
    if imgs.ndim != 3:
        raise ValueError(
            f"images IDX must be [N, rows, cols]; got shape {imgs.shape}"
        )
    if labs.ndim != 1 or len(labs) != len(imgs):
        raise ValueError(
            f"labels IDX must be [N={len(imgs)}]; got shape {labs.shape}"
        )
    image = (imgs.astype(np.float32) / 255.0)[..., None]
    label = labs.astype(np.int32)
    return save_array_store(
        out_path,
        {"image": image, "label": label},
        provenance={
            "format": "mnist-idx",
            "images": os.path.basename(images_path),
            "images_sha256": _sha256(images_path),
            "labels": os.path.basename(labels_path),
            "labels_sha256": _sha256(labels_path),
        },
    )


def ingest_tokens(
    out_path: str, tokens_path: str, seq_len: int, key: str = "tokens"
) -> str:
    """Ingest a tokenized text corpus — a flat binary/.npy array of
    token ids — into fixed-length rows of ``seq_len + 1`` (input +
    shifted-label convention of the LM families).  Leftover tokens past
    the last full row are dropped.  Accepts ``.npy`` or raw little-
    endian uint16/uint32 binary (``.bin`` with dtype inferred from
    size alignment is ambiguous, so raw files must be ``.u16``/
    ``.u32``)."""
    if tokens_path.endswith(".npy"):
        flat = np.load(tokens_path, mmap_mode="r")
    elif tokens_path.endswith(".u16"):
        flat = np.fromfile(tokens_path, "<u2")
    elif tokens_path.endswith(".u32"):
        flat = np.fromfile(tokens_path, "<u4")
    else:
        raise ValueError(
            f"unknown token file type {tokens_path!r} (.npy/.u16/.u32)"
        )
    if flat.ndim != 1:
        raise ValueError(f"token corpus must be flat; got {flat.shape}")
    if not np.issubdtype(flat.dtype, np.integer):
        raise ValueError(
            f"token corpus must hold integer ids; got dtype {flat.dtype} "
            "(a float corpus would silently truncate under astype)"
        )
    if flat.size and int(flat.max()) >= 2**31:
        raise ValueError(
            "token ids exceed int32 range; they would wrap negative and "
            "gather garbage embeddings"
        )
    if flat.size and int(flat.min()) < 0:
        raise ValueError(
            "token corpus contains negative ids (ignore-index sentinels "
            "like -100?); strip them before staging — a negative gather "
            "index trains on garbage embedding rows"
        )
    row = seq_len + 1
    n = len(flat) // row
    if n == 0:
        raise ValueError(
            f"corpus has {len(flat)} tokens, fewer than one {row}-token row"
        )
    rows = np.asarray(flat[: n * row]).reshape(n, row).astype(np.int32)
    return save_array_store(
        out_path,
        {key: rows},
        provenance={
            "format": "tokens",
            "source": os.path.basename(tokens_path),
            "source_sha256": _sha256(tokens_path),
            "seq_len": str(seq_len),
            "dropped_tokens": str(len(flat) - n * row),
        },
    )


def resolve_dataset(
    model, data_dir: str, n_examples: int
) -> Dict[str, np.ndarray]:
    """The one dataset-resolution path every entrypoint shares:
    ``data_dir`` set -> memory-mapped store validated against the
    model; empty -> the model's synthetic data (``n_examples`` rows,
    seed 0 — the staging default, so a staged copy of the synthetic
    set trains bit-identically to the in-memory one)."""
    if data_dir:
        dataset = load_array_store(data_dir)
        validate_for_model(dataset, model)
        return dataset
    from edl_tpu.runtime.data import synthetic_dataset

    return synthetic_dataset(model.synth_batch, n_examples)
