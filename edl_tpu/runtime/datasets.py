"""File-backed datasets: memory-mapped array stores on disk.

The reference delegated real data entirely to the external runtime:
trainer pods received a user ``Workspace``/``TRAINER_PACKAGE`` and the
fault-tolerant master dispatched data-shard *tasks* via etcd
(``/root/reference/pkg/jobparser.go:288-291``; SURVEY.md §5.3).  Here
data is a first-class, deterministic subsystem: an **array store** is a
directory of ``.npy`` files (one per feature) plus a JSON manifest, and
loading it memory-maps every array so trainers stream real bytes from
disk without materializing the dataset in RAM.  A memmapped store plugs
straight into ``ShardedDataIterator`` — batch assembly fancy-indexes
the maps, so only the touched rows are ever paged in — which preserves
the (seed, step) -> indices determinism the elastic protocol depends
on: a resize re-slices the same global batch stream whether the bytes
live in RAM or on disk.

This is the adapter BASELINE configs use for "real data" training
(MNIST/ImageNet-shaped arrays staged to disk once, then trained from
file); any pipeline that can emit numpy arrays (TFDS, webdataset,
tokenized text) stages into it with ``save_array_store``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

MANIFEST = "manifest.json"


def save_array_store(
    path: str, arrays: Dict[str, np.ndarray], seed: Optional[int] = None
) -> str:
    """Write ``arrays`` (shared leading dim) as ``<key>.npy`` files plus
    a manifest.  Atomic enough for the single-writer staging pattern:
    the manifest is written last, so a crashed half-written store fails
    ``load_array_store`` loudly instead of loading short arrays."""
    if not arrays:
        raise ValueError("array store needs at least one array")
    sizes = {k: len(v) for k, v in arrays.items()}
    if len(set(sizes.values())) != 1:
        raise ValueError(f"arrays disagree on leading dim: {sizes}")
    os.makedirs(path, exist_ok=True)
    # Invalidate any existing store FIRST: a crash mid-restage must
    # leave a store that fails load loudly, never an old manifest
    # validating a mix of old and new .npy files.
    try:
        os.remove(os.path.join(path, MANIFEST))
    except FileNotFoundError:
        pass
    meta = {"n": next(iter(sizes.values())), "arrays": {}, "seed": seed}
    for key, v in arrays.items():
        if "/" in key or key.startswith("."):
            raise ValueError(f"bad array key {key!r}")
        np.save(os.path.join(path, f"{key}.npy"), np.asarray(v))
        meta["arrays"][key] = {
            "shape": list(v.shape),
            "dtype": str(np.asarray(v).dtype),
        }
    tmp = os.path.join(path, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(path, MANIFEST))
    return path


def load_array_store(path: str, mmap: bool = True) -> Dict[str, np.ndarray]:
    """Load a store as a dict of (by default) memory-mapped arrays,
    validated against the manifest — shape/dtype drift between staging
    and training fails here, not as a silent garbage batch."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        raise FileNotFoundError(
            f"{path!r} is not an array store (no {MANIFEST}); stage one "
            "with edl_tpu.runtime.datasets.save_array_store"
        )
    with open(mpath) as f:
        meta = json.load(f)
    out: Dict[str, np.ndarray] = {}
    for key, info in meta["arrays"].items():
        v = np.load(
            os.path.join(path, f"{key}.npy"),
            mmap_mode="r" if mmap else None,
        )
        if list(v.shape) != info["shape"] or str(v.dtype) != info["dtype"]:
            raise ValueError(
                f"array {key!r} drifted from manifest: "
                f"{v.shape}/{v.dtype} != {info['shape']}/{info['dtype']}"
            )
        out[key] = v
    return out


def validate_for_model(dataset: Dict[str, np.ndarray], model) -> None:
    """Fail fast — before any compile — when a store doesn't match the
    batches the model's loss reads (a mismatch otherwise surfaces as a
    bare ``KeyError`` or opaque XLA shape error deep inside the jit'd
    step).  The model's own ``synth_batch`` is the shape/dtype
    contract: per-feature trailing dims and dtype must agree."""
    ref = model.synth_batch(np.random.RandomState(0), 1)
    missing = set(ref) - set(dataset)
    if missing:
        raise ValueError(
            f"array store lacks features {sorted(missing)} required by "
            f"model {model.name!r} (store has {sorted(dataset)})"
        )
    for key, want in ref.items():
        got = dataset[key]
        if got.shape[1:] != want.shape[1:]:
            raise ValueError(
                f"array store feature {key!r} has per-example shape "
                f"{tuple(got.shape[1:])}; model {model.name!r} expects "
                f"{tuple(want.shape[1:])}"
            )
        if np.asarray(got).dtype != np.asarray(want).dtype:
            raise ValueError(
                f"array store feature {key!r} has dtype {got.dtype}; "
                f"model {model.name!r} expects {np.asarray(want).dtype}"
            )


def stage_synthetic(
    path: str, model_synth_batch, n_examples: int, seed: int = 0
) -> str:
    """Stage a model's deterministic synthetic dataset to disk — the
    zero-download stand-in for a real corpus that still exercises the
    full file-backed path (mmap -> fancy-index -> device)."""
    rng = np.random.RandomState(seed)
    return save_array_store(path, model_synth_batch(rng, n_examples), seed=seed)


def resolve_dataset(
    model, data_dir: str, n_examples: int
) -> Dict[str, np.ndarray]:
    """The one dataset-resolution path every entrypoint shares:
    ``data_dir`` set -> memory-mapped store validated against the
    model; empty -> the model's synthetic data (``n_examples`` rows,
    seed 0 — the staging default, so a staged copy of the synthetic
    set trains bit-identically to the in-memory one)."""
    if data_dir:
        dataset = load_array_store(data_dir)
        validate_for_model(dataset, model)
        return dataset
    from edl_tpu.runtime.data import synthetic_dataset

    return synthetic_dataset(model.synth_batch, n_examples)
