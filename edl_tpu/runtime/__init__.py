from edl_tpu.runtime.train import TrainState, Trainer
from edl_tpu.runtime.data import ShardedDataIterator

__all__ = ["TrainState", "Trainer", "ShardedDataIterator"]
