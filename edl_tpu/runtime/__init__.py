from edl_tpu.runtime.train import TrainState, Trainer
from edl_tpu.runtime.data import ShardedDataIterator
from edl_tpu.runtime.datasets import (
    ingest_mnist_idx,
    ingest_tokens,
    load_array_store,
    save_array_store,
    stage_synthetic,
)

__all__ = [
    "TrainState",
    "Trainer",
    "ShardedDataIterator",
    "ingest_mnist_idx",
    "ingest_tokens",
    "load_array_store",
    "save_array_store",
    "stage_synthetic",
]
