"""Coordinator as a service: HTTP front-end over ``LocalCoordinator``.

In the deployed reference system, membership truth lived in an etcd
sidecar next to the master (``pkg/jobparser.go:174-232``) and trainers
reached it through env-plumbed endpoints.  Our replacement is one tiny
JSON-over-HTTP service (stdlib only — the pod image needs nothing but
python) exposing exactly the ``LocalCoordinator`` interface; the
``HTTPCoordinator`` client is interface-compatible with
``LocalCoordinator`` so ``ElasticTrainer`` works with either (in-process
for tests/local mode, over the network in a cluster).

Run as a pod: ``python -m edl_tpu.runtime.coord_service --port 7164
--min-world 1 --max-world 8`` (this is the command
``parse_to_coordinator`` bakes into the coordinator Deployment).
"""

from __future__ import annotations

import argparse
import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from edl_tpu.runtime.coordinator import ElasticPlan, LocalCoordinator


def _plan_to_dict(plan: Optional[ElasticPlan]) -> Optional[dict]:
    if plan is None:
        return None
    return {
        "generation": plan.generation,
        "world_size": plan.world_size,
        "members": list(plan.members),
        "restore_step": plan.restore_step,
        "addresses": list(plan.addresses),
        "alive": list(plan.alive),
        "prewarm": plan.prewarm,
        "stop_step": plan.stop_step,
        "trace_id": plan.trace_id,
        "prewarm_trace": plan.prewarm_trace,
    }


def _plan_from_dict(d: Optional[dict]) -> Optional[ElasticPlan]:
    if not d:
        return None
    return ElasticPlan(
        generation=d["generation"],
        world_size=d["world_size"],
        members=tuple(d["members"]),
        restore_step=d.get("restore_step", -1),
        addresses=tuple(d.get("addresses", ())),
        alive=tuple(d.get("alive", ())),
        prewarm=int(d.get("prewarm", 0)),
        stop_step=int(d.get("stop_step", -1)),
        trace_id=str(d.get("trace_id", "")),
        prewarm_trace=str(d.get("prewarm_trace", "")),
    )


class CoordinatorServer:
    """Serve a LocalCoordinator over HTTP.  One POST endpoint per
    coordinator method; GET /plan for the hot-path poll."""

    def __init__(self, coordinator: LocalCoordinator, host: str = "0.0.0.0", port: int = 7164):
        self.coordinator = coordinator
        coord = coordinator

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_text(self, text: str, code=200):
                body = text.encode()
                self.send_response(code)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                u = urlparse(self.path)
                query = parse_qs(u.query)
                if u.path == "/plan":
                    self._reply({"plan": _plan_to_dict(coord.plan())})
                elif u.path == "/members":
                    self._reply({"members": coord.members()})
                elif u.path == "/target":
                    self._reply({"world": coord.target_world()})
                elif u.path == "/metrics":
                    # Registry-backed Prometheus exposition by default;
                    # ?format=json keeps the pre-telemetry dict shape
                    # (HTTPCoordinator.metrics() and the controller's
                    # status scrape depend on it).  Version-skew note:
                    # NEW clients fall back against old servers (404
                    # on the query form -> bare GET), but a
                    # PRE-telemetry client's bare GET against this
                    # server receives text — upgrade control-plane
                    # binaries before (or with) coordinators.
                    if query.get("format", [""])[0] == "json":
                        self._reply(coord.metrics())
                    else:
                        self._reply_text(coord.metrics_text())
                elif u.path == "/telemetry":
                    self._reply(coord.telemetry())
                elif u.path == "/healthz":
                    self._reply({"ok": True})
                else:
                    self._reply({"error": "not found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                try:
                    if self.path == "/register":
                        plan = coord.register(
                            req["trainer_id"],
                            address=req.get("address", ""),
                            replica=req.get("replica"),
                            host=req.get("host"),
                        )
                        self._reply({"plan": _plan_to_dict(plan)})
                    elif self.path == "/deregister":
                        coord.deregister(req["trainer_id"])
                        self._reply({"ok": True})
                    elif self.path == "/heartbeat":
                        # The reply carries the server's wall clock:
                        # with the client's t0/t1 stamps it is one
                        # NTP-style offset sample for the merged
                        # timeline's clock alignment (zero extra
                        # round-trips).  Coordinator doubles without
                        # the return value simply reply without it.
                        r = coord.heartbeat(
                            req["trainer_id"], step=int(req.get("step", -1))
                        )
                        self._reply(
                            {"ok": True, **(r if isinstance(r, dict) else {})}
                        )
                    elif self.path == "/ack":
                        coord.ack_generation(req["trainer_id"], req["generation"])
                        self._reply({"ok": True})
                    elif self.path == "/target":
                        # trace_id: the autoscaler decision's causal
                        # trace, stamped into the retargeted plan.
                        try:
                            coord.set_target_world(
                                req["world"],
                                trace_id=str(req.get("trace_id", "")),
                            )
                        except TypeError:
                            # pre-tracing coordinator double
                            coord.set_target_world(req["world"])
                        self._reply({"ok": True})
                    elif self.path == "/prewarm":
                        # Advisory pre-actuation announcement: trainers
                        # AOT-warm the hinted world size's step before
                        # the retarget lands (zero-stall resize).  The
                        # decision's trace id rides the hint.
                        try:
                            coord.set_prewarm(
                                req["world"],
                                trace_id=str(req.get("trace_id", "")),
                            )
                        except TypeError:
                            coord.set_prewarm(req["world"])
                        self._reply({"ok": True})
                    elif self.path == "/telemetry":
                        # Cumulative per-trainer snapshot + an event
                        # tail, idempotent by (trainer_id, seq) — the
                        # piggyback ride of the heartbeat cadence.
                        try:
                            coord.report_telemetry(
                                req["trainer_id"],
                                snapshot=req.get("snapshot"),
                                seq=int(req.get("seq", 0)),
                                events=req.get("events"),
                                boot=str(req.get("boot", "")),
                                clock=req.get("clock"),
                            )
                        except TypeError:
                            coord.report_telemetry(
                                req["trainer_id"],
                                snapshot=req.get("snapshot"),
                                seq=int(req.get("seq", 0)),
                                events=req.get("events"),
                                boot=str(req.get("boot", "")),
                            )
                        self._reply({"ok": True})
                    elif self.path == "/checkpoint":
                        coord.report_checkpoint(req["step"])
                        self._reply({"ok": True})
                    elif self.path == "/complete":
                        coord.report_complete(req.get("step", -1))
                        self._reply({"ok": True})
                    elif self.path == "/evict_dead":
                        self._reply({"evicted": coord.evict_dead()})
                    else:
                        self._reply({"error": "not found"}, 404)
                except KeyError as e:
                    self._reply({"error": f"unknown trainer: {e}"}, 404)
                except ValueError as e:
                    self._reply({"error": str(e)}, 400)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        self._evict_stop: Optional[threading.Event] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self, evict: bool = True):
        """``evict``: also run the heartbeat-lease reaper (failure
        detection is live only if someone drives ``evict_dead``)."""
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="edl-coord"
        )
        self._thread.start()
        if evict:
            self._evict_stop = threading.Event()
            period = max(self.coordinator._heartbeat_timeout / 2, 0.5)

            def evict_loop():
                while not self._evict_stop.wait(period):
                    self.coordinator.evict_dead()

            threading.Thread(
                target=evict_loop, daemon=True, name="edl-evict"
            ).start()
        return self

    def stop(self):
        if self._evict_stop is not None:
            self._evict_stop.set()
        self._server.shutdown()
        self._server.server_close()


class HTTPCoordinator:
    """Client-side twin of ``LocalCoordinator`` — same methods, same
    types, network underneath.  Injected into ``ElasticTrainer`` by the
    launcher when ``EDL_COORDINATOR_ADDR`` is set."""

    def __init__(
        self,
        address: str,
        timeout: float = 5.0,
        retries: int = 3,
        retry_base_delay: float = 0.2,
        retry_deadline: Optional[float] = None,
        retry_policy=None,
    ):
        """``retries``/``retry_base_delay``/``retry_deadline``
        parameterize the transient-failure backoff (previously
        hardcoded ``0.2 * 2**attempt`` with no deadline): callers
        inside a bounded control tick pass a deadline, the step loop
        keeps the default.  ``retry_policy`` overrides wholesale."""
        from edl_tpu.telemetry.trace import ClockOffsetEstimator
        from edl_tpu.utils.retry import RetryPolicy

        if "://" not in address:
            address = f"http://{address}"
        self.address = address.rstrip("/")
        self.timeout = timeout
        self.retries = retries
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=retries,
            base_delay=retry_base_delay,
            max_delay=2.0,
            deadline=retry_deadline,
        )
        #: NTP-style estimate of the coordinator's clock vs ours, fed
        #: by heartbeat request/response pairs (min-RTT filtered) —
        #: what lets the merged cluster timeline causally order events
        #: across members with skewed wall clocks
        self.clock_estimator = ClockOffsetEstimator()

    def _open(self, req) -> bytes:
        """One raw HTTP round-trip.  The chaos transport wrapper
        (``edl_tpu.chaos.transport``) overrides exactly this seam to
        inject refused connections, timeouts, slow responses, and torn
        JSON under the production retry path."""
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return r.read()

    def _request(self, req) -> dict:
        """All coordinator calls are idempotent (register/heartbeat/ack/
        target re-apply cleanly), so transient network failures retry
        under ``retry_policy`` instead of raising into the step loop."""
        import urllib.error

        from edl_tpu.utils.retry import GiveUpError

        import zlib

        try:
            return self.retry_policy.run(
                lambda: json.loads(self._open(req)),
                # An HTTPError means the server answered: not transient.
                retryable=lambda e: not isinstance(e, urllib.error.HTTPError),
                # Per-client jitter stream (stable, so replays are
                # deterministic; distinct, so N clients retrying after
                # a coordinator restart don't re-hit it in lockstep).
                seed=zlib.crc32(self.address.encode()),
                describe="coordinator request",
            )
        except GiveUpError as e:
            raise ConnectionError(
                f"coordinator unreachable after {e.attempts} tries"
            ) from e.last_error

    def _get(self, path: str) -> dict:
        return self._request(f"{self.address}{path}")

    def _post(self, path: str, **payload) -> dict:
        return self._request(
            urllib.request.Request(
                f"{self.address}{path}",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
        )

    # -- LocalCoordinator interface -----------------------------------------
    def register(
        self,
        trainer_id: str,
        address: str = "",
        replica=None,
        host=None,
    ) -> Optional[ElasticPlan]:
        return _plan_from_dict(
            self._post(
                "/register",
                trainer_id=trainer_id,
                address=address,
                replica=replica,
                host=host,
            )["plan"]
        )

    def deregister(self, trainer_id: str):
        self._post("/deregister", trainer_id=trainer_id)

    def heartbeat(self, trainer_id: str, step: int = -1):
        import time as _time
        import urllib.error

        try:
            t0 = _time.time()
            r = self._post("/heartbeat", trainer_id=trainer_id, step=step)
            t1 = _time.time()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # same contract as LocalCoordinator.heartbeat
                raise KeyError(trainer_id) from None
            raise
        # One free clock-offset sample per beat (retries inflate the
        # apparent RTT; the estimator's min-RTT filter discards them).
        st = r.get("server_time")
        if st is not None:
            self.clock_estimator.add(t0, float(st), t1)

    def ack_generation(self, trainer_id: str, generation: int):
        self._post("/ack", trainer_id=trainer_id, generation=generation)

    def set_target_world(self, n: int, trace_id: str = ""):
        self._post("/target", world=n, trace_id=trace_id)

    def set_prewarm(self, n: int, trace_id: str = ""):
        """Announce the autoscaler's planned next parallelism so
        trainers warm that world size's compiled step ahead of the
        actual retarget (see ``LocalCoordinator.set_prewarm``).  The
        decision's causal-trace id rides the hint."""
        self._post("/prewarm", world=n, trace_id=trace_id)

    def get_target_world(self) -> int:
        return self._get("/target")["world"]

    def report_checkpoint(self, step: int):
        self._post("/checkpoint", step=step)

    def report_complete(self, step: int = -1):
        self._post("/complete", step=step)

    def completed(self) -> bool:
        return bool(self.metrics()["completed"])

    def metrics(self) -> dict:
        """The coordinator snapshot as a dict (the pre-telemetry JSON
        shape, preserved behind ``?format=json`` — the default GET
        /metrics now serves Prometheus text, see ``metrics_text``).
        Falls back to the bare path for PRE-telemetry coordinators
        (exact-path match: ``?format=json`` 404s there, and the bare
        ``/metrics`` still answers the JSON dict)."""
        import urllib.error

        try:
            return self._get("/metrics?format=json")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            return self._get("/metrics")

    def metrics_text(self) -> str:
        """Prometheus text exposition (registry-backed GET /metrics)."""
        url = f"{self.address}/metrics"
        import urllib.error
        import zlib

        from edl_tpu.utils.retry import GiveUpError

        try:
            return self.retry_policy.run(
                lambda: self._open(url).decode(),
                retryable=lambda e: not isinstance(e, urllib.error.HTTPError),
                seed=zlib.crc32(self.address.encode()),
                describe="coordinator metrics scrape",
            )
        except GiveUpError as e:
            raise ConnectionError(
                f"coordinator unreachable after {e.attempts} tries"
            ) from e.last_error

    def report_telemetry(
        self,
        trainer_id: str,
        snapshot: Optional[dict] = None,
        seq: int = 0,
        events: Optional[list] = None,
        boot: str = "",
        clock: Optional[dict] = None,
    ):
        """ONE attempt, no backoff (unlike every other call): the
        report is cumulative and re-sent every cadence anyway, and it
        runs on the trainer's heartbeat thread — a retry storm here
        could outlast the membership lease and evict a healthy member
        for the sake of best-effort telemetry.  ``clock`` defaults to
        this client's own heartbeat-fed offset estimate."""
        if clock is None:
            off = self.clock_estimator.offset()
            if off is not None:
                clock = {"offset": off, "rtt": self.clock_estimator.rtt()}
        payload = {
            "trainer_id": trainer_id,
            "snapshot": snapshot,
            "seq": seq,
            "events": events,
            "boot": boot,
            "clock": clock,
        }
        req = urllib.request.Request(
            f"{self.address}/telemetry",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        json.loads(self._open(req))

    def telemetry(self) -> dict:
        return self._get("/telemetry")

    def evict_dead(self) -> List[str]:
        return self._post("/evict_dead")["evicted"]

    def plan(self) -> Optional[ElasticPlan]:
        return _plan_from_dict(self._get("/plan")["plan"])

    def members(self) -> List[str]:
        return self._get("/members")["members"]


def main(argv=None):  # pragma: no cover - pod entrypoint
    p = argparse.ArgumentParser(description="EDL-TPU coordinator service")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7164)
    p.add_argument("--min-world", type=int, default=1)
    p.add_argument("--max-world", type=int, default=1)
    p.add_argument("--heartbeat-timeout", type=float, default=10.0)
    p.add_argument(
        "--target-steps",
        type=int,
        default=0,
        help="steps after which the job is complete (0 = open-ended; "
        "trainers may still POST /complete explicitly)",
    )
    p.add_argument(
        "--legal-sizes",
        default=None,
        help=(
            "comma-separated legal world sizes; absent = every size legal, "
            "explicitly empty = NO legal size (trainers hold at the barrier)"
        ),
    )
    p.add_argument(
        "--hosts",
        type=int,
        default=1,
        help=(
            "pods per trainer replica (multi-host slice topologies: one "
            "replica = an Indexed Job of this many pods)"
        ),
    )
    args = p.parse_args(argv)
    legal = (
        None
        if args.legal_sizes is None
        else [int(s) for s in args.legal_sizes.split(",") if s]
    )
    coord = LocalCoordinator(
        target_world=args.min_world,
        max_world=args.max_world,
        heartbeat_timeout=args.heartbeat_timeout,
        legal_sizes=legal,
        hosts_per_replica=args.hosts,
    )
    if args.target_steps:
        coord.set_target_steps(args.target_steps)
    server = CoordinatorServer(coord, host=args.host, port=args.port)
    server.start(evict=True)
    print(f"edl-tpu coordinator listening on {args.host}:{server.port}")
    threading.Event().wait()  # serve until killed


if __name__ == "__main__":  # pragma: no cover
    main()
