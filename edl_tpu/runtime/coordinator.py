"""The elastic coordinator — native replacement for master + etcd.

In the reference system, elasticity's *mechanism* lives outside the
repo: a PaddlePaddle master process with an etcd v3.2.1 sidecar
(``pkg/jobparser.go:174-232``) tracks trainer membership and
re-dispatches the data-shard tasks of dead trainers; trainers discover
it via env plumbing (``pkg/jobparser.go:265-313``).  SURVEY.md §5.3
calls this "the heart" of the rebuild.

Our coordinator is deliberately tiny because the TPU design needs far
less: data sharding is a pure function of (seed, step) (see
``runtime/data.py``) so there are no tasks to re-dispatch, and gradient
sync needs no server pool.  What remains is *membership truth*:

- which trainers are alive (heartbeats with a deadline)
- the **generation number** — bumped on every membership/target change
- the agreed target world size (written by the autoscaler's actuation,
  the analog of the reference's Parallelism PUT, ``pkg/autoscaler.go:
  339-376``)
- the checkpoint index (latest durable step), so joiners know where to
  resume from

Trainers poll ``plan()`` between steps; when the plan's generation
differs from theirs they enter the resize barrier (checkpoint, rebuild
mesh, restore — ``runtime/elastic.py``).

``LocalCoordinator`` is the in-process implementation used by the
single-host runtime, tests, and the local CLI mode.  A service version
speaks the same interface over HTTP (``edl_tpu.runtime.coord_service``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from edl_tpu.telemetry import (
    FlightRecorder,
    TelemetryAggregator,
    coord_snapshot_gauges,
    merge_snapshots,
    new_trace_id,
    render_prometheus,
)


@dataclass(frozen=True)
class ElasticPlan:
    """What every trainer must agree on to form a world."""

    generation: int
    world_size: int
    #: member trainer ids in rank order (rank = index)
    members: tuple
    #: step to restore from when joining this generation (-1: fresh init)
    restore_step: int = -1
    #: member addresses in rank order (host:port of each trainer pod) —
    #: rank 0's address seeds ``jax.distributed.initialize`` when the
    #: world spans processes (the launcher's world_builder)
    addresses: tuple = ()
    #: EVERY registered live member (active + standby) at plan time.
    #: The resize flush reads this to decide whether a collective flush
    #: is safe: model-sharded state can only be gathered if every
    #: old-world member is still alive to dispatch the collective
    #: (an evicted/dead one never would — the flush would hang).
    alive: tuple = ()
    #: ADVISORY world size the autoscaler plans to actuate next (0 =
    #: none).  Announced via ``set_prewarm`` BEFORE the retarget/PUT so
    #: trainers AOT-warm exactly the incoming size's step executable
    #: while still stepping at the current one; it rides the plan the
    #: trainers already poll, so the hint costs zero extra round-trips.
    #: Never changes the generation — an updated hint must not push
    #: trainers through a resize barrier.
    prewarm: int = 0
    #: coordinator-stamped stop step for THIS generation's resize: the
    #: last world step any member reported (heartbeat piggyback /
    #: checkpoint reports) plus ``stop_margin`` at plan-rebuild time;
    #: -1 when no step was ever reported.  ADVISORY/JOURNAL ONLY: the
    #: data-plane agreement is the honored boundary — heartbeat lag
    #: makes this stamp stale by up to one cadence, and honoring a
    #: stale stamp below the agreement would re-introduce the
    #: poll-skew teardown race (min(stamped, agreed) floored at the
    #: agreement reduces to the agreement exactly).  Its job is making
    #: the scale-down timeline reconstructible from the journal alone
    #: (``coord.plan`` events + the autoscaler decision log).
    stop_step: int = -1
    #: causal-trace correlation id of the decision that produced THIS
    #: generation (autoscaler-minted and delivered with the retarget /
    #: prewarm hint; coordinator-minted for join/evict/leave rebuilds).
    #: Members install it as their flight recorder's ambient trace, so
    #: every event of the resize — vote, quiesce, flush, transfer,
    #: restore, first step — journals under one id
    #: (``edl_tpu.telemetry.trace``).
    trace_id: str = ""
    #: trace id of the UPCOMING decision announced via the prewarm
    #: hint (same generation — advisory, like ``prewarm`` itself), so
    #: the background AOT warm journals under the decision that asked
    #: for it before the retarget even lands
    prewarm_trace: str = ""


@dataclass
class _Member:
    trainer_id: str
    last_heartbeat: float
    joined_generation: int
    acked_generation: int = -1
    address: str = ""
    #: slice-replica index for multi-host topologies (one trainer
    #: replica = ``hosts_per_replica`` pods, the per-replica Indexed
    #: Job's pods); None for single-host replicas
    replica: Optional[int] = None
    #: host index within the replica (the Job completion index) —
    #: fixes intra-replica rank order so TPU_WORKER_ID agrees
    host: Optional[int] = None


class LocalCoordinator:
    """Thread-safe in-process coordinator.

    Heartbeat liveness replaces etcd leases: a member that misses
    ``heartbeat_timeout`` seconds is evicted and the generation bumps
    (failure detection the reference delegated, SURVEY.md §5.3)."""

    def __init__(
        self,
        target_world: int = 1,
        max_world: int = 0,
        heartbeat_timeout: float = 10.0,
        legal_sizes: Optional[List[int]] = None,
        clock: Callable[[], float] = time.monotonic,
        hosts_per_replica: int = 1,
    ):
        """``legal_sizes``: world sizes the runtime may form (from
        ``TrainingJob.legal_world_sizes()`` — divisors of the global
        batch within [min,max], SURVEY.md §7.4).  The plan quantizes
        down to the largest legal size <= min(members, target); with no
        legal size small enough the plan's world_size is 0 and trainers
        hold at the barrier until membership recovers.

        ``hosts_per_replica``: pods per trainer replica (>1 for
        multi-host slice topologies like v5e-16, where one replica is
        an Indexed Job of ``hosts`` pods).  The plan then counts
        REPLICAS in ``world_size``/targets/legal sizes while
        ``members``/``addresses`` list every pod in replica-major,
        host-minor rank order; only replicas with all their hosts
        registered can join the active world."""
        self._lock = threading.Condition()
        self._members: Dict[str, _Member] = {}
        self._generation = 0
        self._target_world = target_world
        self._max_world = max_world or target_world
        self._heartbeat_timeout = heartbeat_timeout
        # None = every size legal; [] = NO legal size (world_size pins to
        # 0 and trainers hold) — distinct on purpose, see ADVICE r1.
        self._legal_sizes = (
            sorted(set(legal_sizes)) if legal_sizes is not None else None
        )
        if hosts_per_replica < 1:
            raise ValueError("hosts_per_replica must be >= 1")
        self._hosts_per_replica = hosts_per_replica
        self._clock = clock
        self._latest_checkpoint_step = -1
        #: last world step any member reported (heartbeat piggyback or
        #: checkpoint report) — the base of the plan's stop_step stamp
        self._latest_step = -1
        #: steps past the last reported step the stamped stop allows
        #: for in-flight progress (heartbeat-cadence staleness)
        self.stop_margin = 16
        self._prewarm = 0
        #: trace id of the decision currently being actuated (set by
        #: the prewarm announcement and/or the retarget itself;
        #: consumed by the retarget's plan rebuild)
        self._pending_trace = ""
        #: trace id of an actuation still CONVERGING: a scale-up's
        #: retarget rebuild fires before the new pods exist, so the
        #: join rebuilds that grow the world toward the target are part
        #: of the same decision and must journal under the same id —
        #: cleared once the world reaches the target
        self._actuation_trace = ""
        #: generation whose coord.world_acked event already journaled
        self._acked_journaled = -1
        self._plan: Optional[ElasticPlan] = None
        self._resize_log: List[dict] = []
        #: target training steps (passes x batches-per-pass); 0 = open-ended
        self._target_steps = 0
        #: set when a trainer reports the job finished its passes
        self._completed = False
        self._completed_step = -1
        #: cluster-wide telemetry: trainers POST cumulative registry
        #: snapshots (piggybacked on the heartbeat cadence); merge is
        #: idempotent, so a coordinator restart reconverges as soon as
        #: each live trainer's next report lands (edl_tpu.telemetry)
        self._telemetry = TelemetryAggregator(clock=self._clock)
        #: coordinator-side flight recorder: plan rebuilds, evictions,
        #: and the tails trainers piggyback on their telemetry reports
        self._recorder = FlightRecorder(capacity=1024)

    # -- membership (trainer-facing) ----------------------------------------
    def register(
        self,
        trainer_id: str,
        address: str = "",
        replica: Optional[int] = None,
        host: Optional[int] = None,
    ) -> ElasticPlan:
        """Join the job.  Bumps the generation; returns the new plan.
        ``address`` is the member's reachable host:port (used to seed
        the JAX process group when the world spans pods).  Multi-host
        pods pass their replica index and host (completion) index; a
        re-register (rejoin after eviction) preserves a previously
        declared placement when the new call omits it."""
        with self._lock:
            now = self._clock()
            prev = self._members.get(trainer_id)
            if prev is not None:
                if replica is None:
                    replica = prev.replica
                if host is None:
                    host = prev.host
            self._members[trainer_id] = _Member(
                trainer_id=trainer_id,
                last_heartbeat=now,
                joined_generation=self._generation + 1,
                address=address,
                replica=replica,
                host=host,
            )
            self._rebuild_plan("join")
            return self._plan

    def deregister(self, trainer_id: str):
        """Graceful leave (scale-down actuation or shutdown)."""
        with self._lock:
            if self._members.pop(trainer_id, None) is not None:
                self._rebuild_plan("leave")

    def heartbeat(self, trainer_id: str, step: int = -1) -> dict:
        """``step``: the member's last completed world step, piggybacked
        on the beat so retarget plans can stamp a stop_step without an
        extra round-trip (-1 = not reported).  Returns the server's
        wall clock: with the client's t0/t1 stamps around the beat it
        is the NTP-style offset sample the merged-timeline clock
        alignment runs on (``telemetry.trace.ClockOffsetEstimator``) —
        piggybacked so alignment costs zero extra round-trips."""
        with self._lock:
            m = self._members.get(trainer_id)
            if m is None:
                raise KeyError(f"unknown trainer {trainer_id}")
            m.last_heartbeat = self._clock()
            if step > self._latest_step:
                self._latest_step = step
        return {"server_time": time.time()}

    def ack_generation(self, trainer_id: str, generation: int):
        """Trainer reports it has re-meshed into ``generation``.  The
        moment EVERY planned member has acked the current generation is
        journaled once (``coord.world_acked``, under the plan's trace):
        it is the victim-drain signal the autoscaler's scale-down waits
        on before deleting pods, and the merged timeline should show
        it on the coordinator's lane."""
        with self._lock:
            m = self._members.get(trainer_id)
            if m is not None:
                m.acked_generation = generation
                self._lock.notify_all()
            plan = self._plan
            if (
                plan is not None
                and plan.generation > self._acked_journaled
                and all(
                    self._members[t].acked_generation >= plan.generation
                    for t in plan.members
                    if t in self._members
                )
            ):
                self._acked_journaled = plan.generation
                self._recorder.record(
                    "coord.world_acked",
                    {"world_size": plan.world_size},
                    generation=plan.generation,
                    trace=plan.trace_id,
                )

    # -- control (autoscaler/controller-facing) -----------------------------
    def set_target_world(self, n: int, trace_id: str = ""):
        """The actuation analog of the reference's Parallelism PUT
        (``pkg/autoscaler.go:339-376``): declare the desired trainer
        count, clamped to ``max_world``; the plan shrinks immediately
        (members beyond the target drop out of rank order) or grows as
        new trainers register.  ``trace_id``: the autoscaler decision's
        causal-trace id — stamped into the retargeted plan so every
        member journals the whole resize under it."""
        if n < 1:
            raise ValueError("target world must be >= 1")
        with self._lock:
            n = min(n, self._max_world)
            if n == self._target_world:
                # No-op retarget: the decision actuated a target
                # already in place, so no resize will carry its id —
                # drop any pending trace rather than letting a LATER
                # unrelated retarget consume it (mis-attribution).
                self._pending_trace = ""
                return
            if trace_id:
                self._pending_trace = trace_id
            else:
                # A traceless retarget is a DIFFERENT actor (operator
                # CLI, chaos monkey, controller reconcile): a trace
                # staged by an earlier decision — a prewarm whose PUT
                # gave up, a scale-up whose pods never arrived — must
                # not bleed onto this resize or its converging joins.
                self._pending_trace = ""
                self._actuation_trace = ""
            if self._pending_trace:
                # A scale-up retarget usually fires before its pods
                # exist: the active world is unchanged, the rebuild
                # below early-returns, and the decision only LANDS at
                # the later join rebuilds — which must then journal
                # under this id (see _rebuild_plan's join branch).
                self._actuation_trace = self._pending_trace
            self._target_world = n
            self._rebuild_plan("retarget")
            # The pending trace never outlives the retarget call it was
            # staged for: when the rebuild early-returned (active world
            # unchanged — pods not yet registered), leaving it set
            # would hand this decision's id to a LATER unrelated
            # traceless retarget (confirmed mis-attribution); the
            # converging joins use _actuation_trace instead.
            self._pending_trace = ""

    def set_prewarm(self, n: int, trace_id: str = ""):
        """Announce the world size the autoscaler intends to actuate
        next (the prewarm half of the actuation handshake).  Purely
        advisory: the current plan is re-issued with the hint attached
        — SAME generation, so no trainer resizes — and trainers
        background-compile that size's step executable so the upcoming
        retarget's resize window contains zero cold compiles.  ``0``
        clears the hint.  ``trace_id`` rides the hint (and is held for
        the retarget it announces) so the warm-ahead work journals
        under the decision that asked for it."""
        if n < 0:
            raise ValueError("prewarm world must be >= 0")
        with self._lock:
            n = min(n, self._max_world)
            if trace_id:
                self._pending_trace = trace_id
            if n == self._prewarm and not trace_id:
                return
            self._prewarm = n
            if self._plan is not None and (
                self._plan.prewarm != n
                or (trace_id and self._plan.prewarm_trace != trace_id)
            ):
                from dataclasses import replace

                self._plan = replace(
                    self._plan,
                    prewarm=n,
                    prewarm_trace=trace_id or self._plan.prewarm_trace,
                )
            self._lock.notify_all()

    def prewarm_hint(self) -> int:
        with self._lock:
            return self._prewarm

    def evict_dead(self) -> List[str]:
        """Evict members that missed their heartbeat deadline.  Returns
        evicted ids.  Called periodically by whoever hosts the
        coordinator (controller loop or the service's timer)."""
        with self._lock:
            now = self._clock()
            dead = [
                tid
                for tid, m in self._members.items()
                if now - m.last_heartbeat > self._heartbeat_timeout
            ]
            for tid in dead:
                del self._members[tid]
                # Lease expiry evicts the TELEMETRY too (ISSUE 15): a
                # dead replica's frozen snapshot must stop feeding
                # merged observations — its queue-depth gauge would
                # pin the merged max and its latency histogram would
                # haunt every quantile window (a ghost p95 steering
                # the serving lane).  A live-but-evicted member
                # re-registers and re-reports its cumulative snapshot,
                # so the drop always reconverges.
                self._telemetry.drop_source(tid)
            if dead:
                self._recorder.record(
                    "coord.evict",
                    {"members": sorted(dead)},
                    generation=self._generation,
                )
                self._rebuild_plan("evict")
            return dead

    def report_checkpoint(self, step: int):
        with self._lock:
            if step > self._latest_checkpoint_step:
                self._latest_checkpoint_step = step
            if step > self._latest_step:
                self._latest_step = step
            if self._target_steps and step >= self._target_steps:
                self._completed = True
                self._completed_step = max(self._completed_step, step)

    def report_complete(self, step: int = -1):
        """A trainer finished the job's passes (launcher's end-of-run
        signal).  The controller polls ``completed`` and fires
        ``mark_succeeded`` -> ``lifecycle.complete`` (ref ``Complete``,
        ``pkg/trainingjober.go:126-132`` — which nothing ever called)."""
        with self._lock:
            self._completed = True
            self._completed_step = max(self._completed_step, step)
            self._lock.notify_all()

    def set_target_steps(self, n: int):
        with self._lock:
            self._target_steps = max(0, n)

    # -- queries ------------------------------------------------------------
    def plan(self) -> Optional[ElasticPlan]:
        with self._lock:
            return self._plan

    def target_world(self) -> int:
        """Current actuation target — lets the controller reconcile the
        handshake level-triggered (POST a new target only on drift)."""
        with self._lock:
            return self._target_world

    def completed(self) -> bool:
        with self._lock:
            return self._completed

    def metrics(self) -> dict:
        """Observability snapshot (served at the coordinator's /metrics)."""
        with self._lock:
            plan = self._plan
            world_acked = bool(plan) and all(
                self._members[t].acked_generation >= plan.generation
                for t in plan.members
                if t in self._members
            )
            return {
                "generation": self._generation,
                "world_size": self._plan.world_size if self._plan else 0,
                #: every current-plan member has re-meshed into this
                #: generation — the scale-down actuation's "victims have
                #: quiesced" signal (the new world cannot form until the
                #: old one fully left the agreed stop boundary)
                "world_acked": world_acked,
                "acked_members": sum(
                    1
                    for m in self._members.values()
                    if m.acked_generation >= 0
                ),
                "members": len(self._members),
                "standby": max(
                    0,
                    len(self._members)
                    - (self._plan.world_size if self._plan else 0),
                ),
                "target_world": self._target_world,
                "prewarm": self._prewarm,
                "target_steps": self._target_steps,
                "latest_checkpoint_step": self._latest_checkpoint_step,
                "resizes": len(self._resize_log),
                "completed": self._completed,
                "completed_step": self._completed_step,
            }

    def metrics_text(self) -> str:
        """Prometheus text exposition: the coordinator snapshot as
        gauges, merged with the trainers' reported telemetry (the
        registry-backed replacement for the ad-hoc JSON ``/metrics``;
        the JSON shape survives behind ``?format=json``).  The
        aggregator read holds the lock — the ThreadingHTTPServer can
        run a scrape concurrently with a trainer's POST /telemetry,
        and the aggregator has no lock of its own."""
        with self._lock:
            trainers = self._telemetry.merged()
        merged = merge_snapshots(
            [coord_snapshot_gauges(self.metrics()), trainers]
        )
        return render_prometheus(merged)

    # -- telemetry (trainer-facing) ------------------------------------------
    def report_telemetry(
        self,
        trainer_id: str,
        snapshot: Optional[dict] = None,
        seq: int = 0,
        events: Optional[List[dict]] = None,
        boot: str = "",
        clock: Optional[dict] = None,
    ) -> None:
        """Ingest one trainer's cumulative telemetry report: the
        registry snapshot (idempotently merged by (trainer_id, boot,
        seq) — a restarted trainer's fresh boot supersedes its dead
        incarnation's high seq), a tail of its flight-recorder events,
        and its clock-offset estimate (the merged timeline's
        alignment input)."""
        with self._lock:
            fresh = self._telemetry.report(
                trainer_id, snapshot or {}, seq, boot=boot, clock=clock
            )
        if fresh and events:
            self._recorder.record(
                "coord.telemetry",
                {"source": trainer_id, "events": len(events)},
            )
            self._recorder.ingest(events, origin=trainer_id)

    def telemetry(self) -> dict:
        """Merged cluster telemetry + derived goodput signals (the
        autoscaler's decision-log inputs) + recent flight events +
        per-member clock offsets (the merged-timeline alignment)."""
        with self._lock:
            merged = self._telemetry.merged()
            rate = self._telemetry.step_rate()
            cost = self._telemetry.resize_cost_seconds(merged=merged)
            goodput = self._telemetry.goodput(merged=merged)
            sources = self._telemetry.sources()
            offsets = self._telemetry.clock_offsets()
        return {
            "merged": merged,
            "step_rate": rate,
            "resize_cost_seconds": cost,
            "goodput": goodput,
            "sources": sources,
            "clock_offsets": offsets,
            "events": [e.to_dict() for e in self._recorder.events(256)],
        }

    def recorder(self) -> FlightRecorder:
        return self._recorder

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def latest_checkpoint_step(self) -> int:
        with self._lock:
            return self._latest_checkpoint_step

    def resize_log(self) -> List[dict]:
        with self._lock:
            return list(self._resize_log)

    def wait_all_acked(self, generation: int, timeout: float = 60.0) -> bool:
        """Block until every planned member acked ``generation`` (the
        resize barrier's coordinator side)."""
        deadline = self._clock() + timeout
        with self._lock:
            while True:
                plan = self._plan
                if plan is not None and plan.generation >= generation:
                    acked = all(
                        self._members[tid].acked_generation >= plan.generation
                        for tid in plan.members
                        if tid in self._members
                    )
                    if acked:
                        return True
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                self._lock.wait(timeout=min(remaining, 0.5))

    # -- internals ----------------------------------------------------------
    def _active_members(self) -> tuple:
        """(active_member_ids, world_size_in_replicas) under the current
        membership/target.  Caller holds the lock.

        Single-host (hosts_per_replica == 1): rank order is join order
        (dict preserves insertion); members beyond the target wait in
        standby (they keep heartbeating and join when the target grows
        — the analog of pending pods the kube Job controller folds in).

        Multi-host: members group into replicas by their declared
        replica index; only COMPLETE replicas (all ``hosts`` pods
        present with distinct host indexes) are eligible, taken in
        ascending replica order (the actuation creates/deletes the
        highest-indexed per-replica Jobs, so lowest-indexed survive
        scale-down).  Rank order is replica-major, host-minor — the
        order the slice's TPU_WORKER_IDs expect."""
        hosts = self._hosts_per_replica
        if hosts == 1:
            alive = list(self._members)
            world = min(len(alive), self._target_world, self._max_world)
            if self._legal_sizes is not None:
                fitting = [s for s in self._legal_sizes if s <= world]
                world = fitting[-1] if fitting else 0
            return tuple(alive[:world]), world

        groups: Dict[int, Dict[int, str]] = {}
        for tid, m in self._members.items():
            if m.replica is None or m.host is None:
                continue  # unplaceable pod: cannot join a sliced world
            groups.setdefault(m.replica, {})[m.host] = tid
        complete = sorted(
            r
            for r, g in groups.items()
            if len(g) == hosts and set(g) == set(range(hosts))
        )
        world = min(len(complete), self._target_world, self._max_world)
        if self._legal_sizes is not None:
            fitting = [s for s in self._legal_sizes if s <= world]
            world = fitting[-1] if fitting else 0
        active = tuple(
            groups[r][h] for r in complete[:world] for h in range(hosts)
        )
        return active, world

    def _rebuild_plan(self, reason: str):
        """Recompute the plan after any membership/target change.  Caller
        holds the lock."""
        active, world = self._active_members()
        addresses = tuple(self._members[t].address for t in active)
        if (
            self._plan is not None
            and self._plan.members == active
            and self._plan.addresses == addresses
            and self._plan.world_size == world
        ):
            # The change touched only standby membership (e.g. an extra
            # pod joined beyond the target, or a standby left): the
            # active world is identical, so don't force trainers
            # through a needless resize barrier.
            self._lock.notify_all()
            return
        self._generation += 1
        stop_step = (
            self._latest_step + self.stop_margin
            if self._latest_step >= 0
            else -1
        )
        # The causal-trace id of THIS generation: a retarget consumes
        # the actuation's pending trace (delivered with the prewarm
        # hint and/or the retarget PUT); every other rebuild — join,
        # leave, eviction — mints its own, so membership-churn resizes
        # are just as traceable as autoscaler decisions.  Random, and
        # carried only in non-identity journal fields: chaos-soak
        # digests stay bit-identical.
        prev_world = self._plan.world_size if self._plan else 0
        if reason == "retarget" and self._pending_trace:
            trace = self._pending_trace
            self._pending_trace = ""
        elif (
            reason == "join"
            and self._actuation_trace
            and prev_world < world <= self._target_world
        ):
            # A pod registering while a traced scale-up is still
            # converging IS that actuation landing: the generation the
            # members actually resize into must journal under the
            # decision's id, not a fresh join-minted one.
            trace = self._actuation_trace
        else:
            # Other join/evict/leave rebuilds mint their own id and do
            # NOT consume a pending actuation trace: a pod registering
            # between the prewarm announcement and the retarget must
            # not steal the decision's id from the retarget it tags.
            trace = new_trace_id()
        if world >= self._target_world:
            self._actuation_trace = ""  # actuation converged
        self._plan = ElasticPlan(
            generation=self._generation,
            world_size=world,
            members=active,
            restore_step=self._latest_checkpoint_step,
            addresses=addresses,
            alive=tuple(self._members),
            prewarm=self._prewarm,
            stop_step=stop_step,
            trace_id=trace,
        )
        self._resize_log.append(
            {
                "t": self._clock(),
                "generation": self._generation,
                "reason": reason,
                "world_size": world,
                "members": active,
            }
        )
        self._recorder.record(
            "coord.plan",
            {
                "reason": reason,
                "world_size": world,
                "members": list(active),
                "stop_step": stop_step,
            },
            generation=self._generation,
            trace=trace,
        )
        self._lock.notify_all()
