"""Deterministic sharded data iteration.

In the reference system, data sharding under elasticity is the
fault-tolerant master's job: it hands out data-shard *tasks* via etcd so
dead trainers' shards get re-dispatched (SURVEY.md §5.3; the master is
external, ``pkg/jobparser.go:194-232``).  The TPU-native design needs no
task queue: make the global batch for step ``k`` a **pure function of
(seed, step)**, and give each trainer the ``rank``-th contiguous slice.
Then any membership change is automatically consistent — a new world
size just re-slices the same deterministic global batch stream, and
resume-after-restore replays from the checkpointed step with identical
data.  (This is the fixed-global-batch policy of SURVEY.md §7.4: LR and
batch semantics are invariant to world size.)
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardedDataIterator:
    """Index-based deterministic iterator over an in-memory dataset.

    ``dataset`` is a dict of host numpy arrays sharing a leading
    dimension.  Epoch shuffles are derived from ``seed`` and the epoch
    number only, so two trainers (or the same trainer before and after a
    resize) agree on every batch without communicating.
    """

    def __init__(
        self,
        dataset: Dict[str, np.ndarray],
        global_batch_size: int,
        seed: int = 0,
    ):
        if not dataset:
            raise ValueError("dataset must be non-empty")
        sizes = {k: len(v) for k, v in dataset.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"dataset arrays disagree on length: {sizes}")
        self.dataset = dataset
        self.n = next(iter(sizes.values()))
        if global_batch_size <= 0:
            raise ValueError("global_batch_size must be positive")
        if global_batch_size > self.n:
            raise ValueError(
                f"global_batch_size {global_batch_size} exceeds dataset size {self.n}"
            )
        self.global_batch_size = global_batch_size
        self.seed = seed
        self.batches_per_epoch = self.n // global_batch_size

    # -- determinism core ---------------------------------------------------
    def global_indices(self, step: int) -> np.ndarray:
        """Dataset indices of step ``step``'s global batch (pure function)."""
        if step < 0:
            raise ValueError("step must be >= 0")
        epoch, within = divmod(step, self.batches_per_epoch)
        perm = np.random.RandomState(
            (self.seed * 1_000_003 + epoch) % (2**32)
        ).permutation(self.n)
        lo = within * self.global_batch_size
        return perm[lo : lo + self.global_batch_size]

    def host_batch(
        self, step: int, world: int = 1, rank: int = 0
    ) -> Dict[str, np.ndarray]:
        """Rank-local slice of the global batch for ``step``.

        The global batch is always the same for a given step; ``world``
        only controls how it is sliced (ref contrast: pserver sharding
        pinned counts at job start, ``pkg/jobparser.go:298``)."""
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world {world}")
        if self.global_batch_size % world != 0:
            raise ValueError(
                f"global batch {self.global_batch_size} not divisible by world {world}"
            )
        idx = self.global_indices(step)
        per = self.global_batch_size // world
        sl = idx[rank * per : (rank + 1) * per]
        return {k: v[sl] for k, v in self.dataset.items()}

    def batch_extent(self, mesh: Mesh, batch_axes=("dp",)) -> int:
        """Number of batch-dim shards ``device_batch`` will cut on
        ``mesh``: the product of the present batch axes' sizes (NOT the
        total device count — a tp/sp-bearing mesh replicates the batch
        over its non-batch axes)."""
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        extent = 1
        for a in batch_axes:
            if a in sizes:
                extent *= sizes[a]
        return extent

    def validate_mesh(self, mesh: Mesh, batch_axes=("dp",)) -> None:
        """Raise the real cause when the global batch can't shard on
        ``mesh`` — callers on the resize path check this BEFORE the
        step loop, whose broken-world guard would misread an XLA
        sharding error as membership churn."""
        extent = self.batch_extent(mesh, batch_axes)
        if self.global_batch_size % extent != 0:
            raise ValueError(
                f"global batch {self.global_batch_size} not divisible by "
                f"the mesh's {extent}-way batch extent (axes {batch_axes})"
            )

    # -- abstract schema ----------------------------------------------------
    def abstract_batch(self, mesh: Mesh, batch_axes=("dp",)) -> Dict[str, Any]:
        """ShapeDtypeStructs (with shardings) matching exactly what
        ``device_batch`` would place on ``mesh`` — the batch half of
        allocation-free AOT step warming (``Trainer.warm_step``): N
        world sizes can be pre-lowered without staging a single batch
        on device."""
        axes = tuple(a for a in batch_axes if a in mesh.axis_names)
        lead = axes if len(axes) > 1 else (axes[0] if axes else None)
        self.validate_mesh(mesh, batch_axes)

        def spec_for(ndim: int) -> P:
            return P(lead, *([None] * (ndim - 1)))

        return {
            k: jax.ShapeDtypeStruct(
                (self.global_batch_size,) + v.shape[1:],
                v.dtype,
                sharding=NamedSharding(mesh, spec_for(v.ndim)),
            )
            for k, v in self.dataset.items()
        }

    # -- device placement ---------------------------------------------------
    def device_batch(self, step: int, mesh: Mesh, batch_axes=("dp",)) -> Dict[str, Any]:
        """Global batch placed on ``mesh``, batch dim sharded over
        ``batch_axes``.

        Single-process path: materialize the global batch and let
        ``jax.device_put`` scatter it.  Multi-process path: each process
        materializes only the rows its addressable devices shard, served
        per-device via ``jax.make_array_from_callback`` — driven by the
        sharding itself, so it stays correct for any devices-per-process
        (multi-chip pods, multi-host slices), where slicing by
        ``process_index`` would only cover the 1-chip-per-pod case (the
        multi-host analog of the reference's per-trainer data streams)."""
        axes = tuple(a for a in batch_axes if a in mesh.axis_names)
        lead = axes if len(axes) > 1 else (axes[0] if axes else None)
        self.validate_mesh(mesh, batch_axes)

        def spec_for(ndim: int) -> P:
            return P(lead, *([None] * (ndim - 1)))

        if jax.process_count() > 1:  # pragma: no cover - needs real multi-host
            idx = self.global_indices(step)
            out = {}
            for k, v in self.dataset.items():
                sharding = NamedSharding(mesh, spec_for(v.ndim))
                gshape = (self.global_batch_size,) + v.shape[1:]

                def cb(index, v=v):
                    # index: per-device global-slice tuple; rows of the
                    # deterministic global batch this device holds.
                    return v[idx[index[0]]]

                out[k] = jax.make_array_from_callback(gshape, sharding, cb)
            return out
        gb = {k: v[self.global_indices(step)] for k, v in self.dataset.items()}
        return {
            k: jax.device_put(v, NamedSharding(mesh, spec_for(v.ndim)))
            for k, v in gb.items()
        }


class BatchStager:
    """Background device-batch prefetcher for the steady-state pipeline.

    One worker thread builds and places ``device_batch(step)`` for steps
    ahead of the consumer, so the host-side batch assembly (memmap
    fancy-index + device placement) overlaps the previous step's device
    compute instead of serializing with it.  Because the global batch is
    a pure function of ``(seed, step)`` (the determinism core above),
    prefetching changes WHEN a batch is built, never WHAT it contains —
    the batch stream is bit-identical with the stager on or off.

    Staged batches are keyed by a caller-supplied ``key`` (the elastic
    runtime passes its plan generation): ``rebind(mesh, key)`` with a
    new key drops everything staged for the old mesh, so a batch placed
    on a pre-resize mesh can never be dispatched after the world
    changed.  A worker failure (or chaos ``stage.batch.failed``) marks
    the step failed and the consumer falls back to staging
    synchronously — prefetch is an optimization, never a correctness
    dependency.
    """

    #: how long ``get`` waits on an in-flight staging before giving up
    #: and staging synchronously (the worker resolves every task, so
    #: this only fires if the worker thread itself died)
    WAIT_TIMEOUT = 60.0

    def __init__(
        self,
        data: ShardedDataIterator,
        depth: int = 2,
        batch_axes=("dp",),
        chaos=None,
    ):
        self.data = data
        self.depth = max(1, int(depth))
        self.batch_axes = tuple(batch_axes)
        self.chaos = chaos
        self._cv = threading.Condition()
        self._key: Any = None
        self._mesh: Optional[Mesh] = None
        self._ready: Dict[int, Any] = {}
        self._failed: set = set()
        self._inflight: Optional[int] = None
        self._queue: deque = deque()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"staged": 0, "hits": 0, "misses": 0, "failures": 0}
        from edl_tpu import telemetry

        self._m_stage_seconds = telemetry.get_registry().histogram(
            "edl_batch_stage_seconds"
        )

    # -- lifecycle -----------------------------------------------------------
    def rebind(self, mesh: Mesh, key: Any) -> None:
        """Point the stager at ``mesh`` under cache key ``key``.  A new
        key invalidates everything staged or queued for the old one."""
        with self._cv:
            if key == self._key and mesh is self._mesh:
                return
            self._key = key
            self._mesh = mesh
            self._ready.clear()
            self._failed.clear()
            self._queue.clear()
            self._cv.notify_all()

    def invalidate(self, join: bool = False) -> None:
        """Drop every staged/queued batch.  ``join=True`` additionally
        waits (bounded) for an in-flight staging to finish — callers
        tearing down a device backend must not leave the worker's
        ``device_put`` racing the teardown."""
        with self._cv:
            self._key = None
            self._mesh = None
            self._ready.clear()
            self._failed.clear()
            self._queue.clear()
            self._cv.notify_all()
            if join:
                self._cv.wait_for(
                    lambda: self._inflight is None, timeout=10.0
                )

    # -- consumer API --------------------------------------------------------
    def get(self, step: int, horizon: Optional[int] = None) -> Any:
        """The device batch for ``step``, from the prefetch cache when
        staged (or in flight), synchronously otherwise; then tops the
        prefetch window back up to ``depth`` steps ahead (bounded by
        ``horizon``, the run's target step, when given)."""
        with self._cv:
            mesh, key = self._mesh, self._key
            if mesh is None:
                raise RuntimeError("BatchStager.get before rebind()")
            batch = self._ready.pop(step, None)
            if batch is None and step in self._queue:
                # Not started yet: reclaim it and build synchronously
                # (waiting on the worker here would serialize for no
                # overlap gain).
                self._queue.remove(step)
            elif batch is None and step == self._inflight:
                self._cv.wait_for(
                    lambda: step != self._inflight or self._key != key,
                    timeout=self.WAIT_TIMEOUT,
                )
                batch = self._ready.pop(step, None)
            self._failed.discard(step)
            # Drop anything staged for already-consumed steps (a replay
            # restart re-keys instead, but belt-and-braces here keeps
            # the cache from pinning stale device arrays).
            for s in [s for s in self._ready if s <= step]:
                del self._ready[s]
            if batch is not None:
                self.stats["hits"] += 1
            else:
                self.stats["misses"] += 1
        if batch is None:
            t0 = time.perf_counter()
            batch = self.data.device_batch(
                step, mesh, batch_axes=self.batch_axes
            )
            self._m_stage_seconds.observe(time.perf_counter() - t0)
        self._schedule_ahead(step, horizon)
        return batch

    def _schedule_ahead(self, step: int, horizon: Optional[int]) -> None:
        last = step + self.depth
        if horizon is not None:
            last = min(last, horizon - 1)
        with self._cv:
            if self._mesh is None:
                return
            for s in range(step + 1, last + 1):
                if (
                    s in self._ready
                    or s in self._queue
                    or s == self._inflight
                    or s in self._failed
                ):
                    continue
                self._queue.append(s)
            if self._queue and (
                self._thread is None or not self._thread.is_alive()
            ):
                self._thread = threading.Thread(
                    target=self._work, daemon=True, name="edl-batch-stager"
                )
                self._thread.start()
            self._cv.notify_all()

    # -- worker --------------------------------------------------------------
    def _work(self) -> None:
        while True:
            with self._cv:
                while not self._queue:
                    if not self._cv.wait(timeout=5.0):
                        return  # idle out; get() respawns on demand
                step = self._queue.popleft()
                mesh, key = self._mesh, self._key
                self._inflight = step
            try:
                chaos = self.chaos
                if chaos is not None:
                    for ev in chaos.due("stage.batch.slow"):
                        time.sleep(float(ev.arg or 0.05))
                    chaos.maybe_raise("stage.batch.failed")
                t0 = time.perf_counter()
                batch = self.data.device_batch(
                    step, mesh, batch_axes=self.batch_axes
                )
                self._m_stage_seconds.observe(time.perf_counter() - t0)
            except Exception:
                with self._cv:
                    self._inflight = None
                    if self._key == key and self._mesh is mesh:
                        self._failed.add(step)
                        self.stats["failures"] += 1
                    self._cv.notify_all()
                continue
            with self._cv:
                self._inflight = None
                # Publish only if BOTH the key and the mesh this batch
                # was placed on are still current: a same-generation
                # world re-formation (state-loss recovery) rebinds with
                # an identical key but a fresh mesh — a batch built for
                # the torn-down mesh must never be served as a hit.
                if self._key == key and self._mesh is mesh:
                    self._ready[step] = batch
                    self.stats["staged"] += 1
                self._cv.notify_all()


def synthetic_dataset(
    model_synth_batch, n_examples: int, seed: int = 0
) -> Dict[str, np.ndarray]:
    """Materialize a fixed synthetic dataset from a ModelDef's batch
    generator (deterministic in ``seed``)."""
    rng = np.random.RandomState(seed)
    return model_synth_batch(rng, n_examples)
