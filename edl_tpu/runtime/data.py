"""Deterministic sharded data iteration.

In the reference system, data sharding under elasticity is the
fault-tolerant master's job: it hands out data-shard *tasks* via etcd so
dead trainers' shards get re-dispatched (SURVEY.md §5.3; the master is
external, ``pkg/jobparser.go:194-232``).  The TPU-native design needs no
task queue: make the global batch for step ``k`` a **pure function of
(seed, step)**, and give each trainer the ``rank``-th contiguous slice.
Then any membership change is automatically consistent — a new world
size just re-slices the same deterministic global batch stream, and
resume-after-restore replays from the checkpointed step with identical
data.  (This is the fixed-global-batch policy of SURVEY.md §7.4: LR and
batch semantics are invariant to world size.)
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardedDataIterator:
    """Index-based deterministic iterator over an in-memory dataset.

    ``dataset`` is a dict of host numpy arrays sharing a leading
    dimension.  Epoch shuffles are derived from ``seed`` and the epoch
    number only, so two trainers (or the same trainer before and after a
    resize) agree on every batch without communicating.
    """

    def __init__(
        self,
        dataset: Dict[str, np.ndarray],
        global_batch_size: int,
        seed: int = 0,
    ):
        if not dataset:
            raise ValueError("dataset must be non-empty")
        sizes = {k: len(v) for k, v in dataset.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"dataset arrays disagree on length: {sizes}")
        self.dataset = dataset
        self.n = next(iter(sizes.values()))
        if global_batch_size <= 0:
            raise ValueError("global_batch_size must be positive")
        if global_batch_size > self.n:
            raise ValueError(
                f"global_batch_size {global_batch_size} exceeds dataset size {self.n}"
            )
        self.global_batch_size = global_batch_size
        self.seed = seed
        self.batches_per_epoch = self.n // global_batch_size

    # -- determinism core ---------------------------------------------------
    def global_indices(self, step: int) -> np.ndarray:
        """Dataset indices of step ``step``'s global batch (pure function)."""
        if step < 0:
            raise ValueError("step must be >= 0")
        epoch, within = divmod(step, self.batches_per_epoch)
        perm = np.random.RandomState(
            (self.seed * 1_000_003 + epoch) % (2**32)
        ).permutation(self.n)
        lo = within * self.global_batch_size
        return perm[lo : lo + self.global_batch_size]

    def host_batch(
        self, step: int, world: int = 1, rank: int = 0
    ) -> Dict[str, np.ndarray]:
        """Rank-local slice of the global batch for ``step``.

        The global batch is always the same for a given step; ``world``
        only controls how it is sliced (ref contrast: pserver sharding
        pinned counts at job start, ``pkg/jobparser.go:298``)."""
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world {world}")
        if self.global_batch_size % world != 0:
            raise ValueError(
                f"global batch {self.global_batch_size} not divisible by world {world}"
            )
        idx = self.global_indices(step)
        per = self.global_batch_size // world
        sl = idx[rank * per : (rank + 1) * per]
        return {k: v[sl] for k, v in self.dataset.items()}

    def batch_extent(self, mesh: Mesh, batch_axes=("dp",)) -> int:
        """Number of batch-dim shards ``device_batch`` will cut on
        ``mesh``: the product of the present batch axes' sizes (NOT the
        total device count — a tp/sp-bearing mesh replicates the batch
        over its non-batch axes)."""
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        extent = 1
        for a in batch_axes:
            if a in sizes:
                extent *= sizes[a]
        return extent

    def validate_mesh(self, mesh: Mesh, batch_axes=("dp",)) -> None:
        """Raise the real cause when the global batch can't shard on
        ``mesh`` — callers on the resize path check this BEFORE the
        step loop, whose broken-world guard would misread an XLA
        sharding error as membership churn."""
        extent = self.batch_extent(mesh, batch_axes)
        if self.global_batch_size % extent != 0:
            raise ValueError(
                f"global batch {self.global_batch_size} not divisible by "
                f"the mesh's {extent}-way batch extent (axes {batch_axes})"
            )

    # -- abstract schema ----------------------------------------------------
    def abstract_batch(self, mesh: Mesh, batch_axes=("dp",)) -> Dict[str, Any]:
        """ShapeDtypeStructs (with shardings) matching exactly what
        ``device_batch`` would place on ``mesh`` — the batch half of
        allocation-free AOT step warming (``Trainer.warm_step``): N
        world sizes can be pre-lowered without staging a single batch
        on device."""
        axes = tuple(a for a in batch_axes if a in mesh.axis_names)
        lead = axes if len(axes) > 1 else (axes[0] if axes else None)
        self.validate_mesh(mesh, batch_axes)

        def spec_for(ndim: int) -> P:
            return P(lead, *([None] * (ndim - 1)))

        return {
            k: jax.ShapeDtypeStruct(
                (self.global_batch_size,) + v.shape[1:],
                v.dtype,
                sharding=NamedSharding(mesh, spec_for(v.ndim)),
            )
            for k, v in self.dataset.items()
        }

    # -- device placement ---------------------------------------------------
    def device_batch(self, step: int, mesh: Mesh, batch_axes=("dp",)) -> Dict[str, Any]:
        """Global batch placed on ``mesh``, batch dim sharded over
        ``batch_axes``.

        Single-process path: materialize the global batch and let
        ``jax.device_put`` scatter it.  Multi-process path: each process
        materializes only the rows its addressable devices shard, served
        per-device via ``jax.make_array_from_callback`` — driven by the
        sharding itself, so it stays correct for any devices-per-process
        (multi-chip pods, multi-host slices), where slicing by
        ``process_index`` would only cover the 1-chip-per-pod case (the
        multi-host analog of the reference's per-trainer data streams)."""
        axes = tuple(a for a in batch_axes if a in mesh.axis_names)
        lead = axes if len(axes) > 1 else (axes[0] if axes else None)
        self.validate_mesh(mesh, batch_axes)

        def spec_for(ndim: int) -> P:
            return P(lead, *([None] * (ndim - 1)))

        if jax.process_count() > 1:  # pragma: no cover - needs real multi-host
            idx = self.global_indices(step)
            out = {}
            for k, v in self.dataset.items():
                sharding = NamedSharding(mesh, spec_for(v.ndim))
                gshape = (self.global_batch_size,) + v.shape[1:]

                def cb(index, v=v):
                    # index: per-device global-slice tuple; rows of the
                    # deterministic global batch this device holds.
                    return v[idx[index[0]]]

                out[k] = jax.make_array_from_callback(gshape, sharding, cb)
            return out
        gb = {k: v[self.global_indices(step)] for k, v in self.dataset.items()}
        return {
            k: jax.device_put(v, NamedSharding(mesh, spec_for(v.ndim)))
            for k, v in gb.items()
        }


def synthetic_dataset(
    model_synth_batch, n_examples: int, seed: int = 0
) -> Dict[str, np.ndarray]:
    """Materialize a fixed synthetic dataset from a ModelDef's batch
    generator (deterministic in ``seed``)."""
    rng = np.random.RandomState(seed)
    return model_synth_batch(rng, n_examples)
