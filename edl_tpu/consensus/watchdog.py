"""Collective watchdog: a deadline on in-flight step/control futures.

gloo collectives have no native timeout: when a peer stands down (or
wedges) mid-step, the survivor's blocking device fetch waits forever —
the exact hang the step bus prevents for COORDINATED teardowns.  The
watchdog is the backstop for everything else: each harvest-time fetch
runs on a reusable helper thread with a deadline; on expiry the fetch
thread is abandoned (it is stuck inside C++ — it leaks with the dead
world's handles, exactly like the launcher's world graveyard) and
``CollectiveTimeout`` raises into the harvest path, where the shared
``_absorb_step_failure`` recovery buries the world and holds for a
fresh generation instead of hanging until the test/job timeout.

Chaos: ``consensus.watchdog.trip`` simulates a wedged collective
deterministically (the fetch reports expiry without waiting), so the
recovery path is testable in any world.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional


class CollectiveTimeout(RuntimeError):
    """An in-flight step/control future missed the watchdog deadline:
    the collective is considered wedged (peer stood down or died
    silently) and the world must be buried and re-formed."""


class CollectiveWatchdog:
    """Deadline-guarded fetches.  ``timeout <= 0`` disables the guard
    (fetches run inline — the single-process default, where a wedge is
    impossible and the thread hop would be pure overhead).

    Reusers outside the consensus lane (the serving engine's decode
    dispatch watchdog, ISSUE 15) keep the deadline-fetch machinery but
    swap the *names*: ``chaos_check`` replaces the default
    ``consensus.watchdog.trip`` chaos probe and ``on_trip`` replaces
    the default consensus counter + flight event — both are plain
    callables so every metric / event / chaos literal stays at ITS
    call site (the lint gates check literals, not plumbing)."""

    def __init__(
        self,
        timeout: float = 0.0,
        chaos=None,
        registry=None,
        recorder=None,
        chaos_check: Optional[Callable[[], bool]] = None,
        on_trip: Optional[Callable[[str, float], None]] = None,
    ):
        from edl_tpu import telemetry

        self.timeout = timeout
        self.chaos = chaos
        self.registry = registry if registry is not None else telemetry.get_registry()
        self.recorder = recorder if recorder is not None else telemetry.get_recorder()
        self._m_trips = self.registry.counter(
            "edl_consensus_watchdog_trips_total"
        )
        self.chaos_check = chaos_check
        self.on_trip = on_trip
        self.trips = 0
        self._lock = threading.Lock()
        self._q: Optional[queue.SimpleQueue] = None
        self._worker: Optional[threading.Thread] = None

    # -- worker --------------------------------------------------------------
    def _ensure_worker(self) -> queue.SimpleQueue:
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._q = queue.SimpleQueue()
                self._worker = threading.Thread(
                    target=self._loop,
                    args=(self._q,),
                    daemon=True,
                    name="edl-collective-fetch",
                )
                self._worker.start()
            return self._q

    @staticmethod
    def _loop(q: queue.SimpleQueue) -> None:
        while True:
            task = q.get()
            if task is None:
                return
            fn, box, done = task
            try:
                box["val"] = fn()
            except BaseException as e:  # delivered to the waiter
                box["err"] = e
            done.set()

    def _abandon_worker(self) -> None:
        """The worker is stuck inside a wedged collective: forget it
        (the thread leaks with the dead world — un-joinable by design)
        and let the next fetch start fresh."""
        with self._lock:
            self._worker = None
            self._q = None

    def _trip(self, what: str, waited: float) -> None:
        self.trips += 1
        if self.on_trip is not None:
            # Reuser-owned accounting (e.g. the serving dispatch
            # watchdog's edl_serve_dispatch_wedged_total +
            # serve.watchdog event) — the consensus names stay out of
            # lanes that aren't the consensus lane.
            self.on_trip(what, waited)
            return
        self._m_trips.inc()
        self.recorder.record(
            "consensus.watchdog",
            {"what": what, "waited_s": round(waited, 3)},
        )

    # -- the guarded fetch ---------------------------------------------------
    def fetch(self, fn: Callable, what: str = "step"):
        """Run ``fn`` (a blocking device fetch) under the deadline.
        Raises ``CollectiveTimeout`` on expiry or a due
        ``consensus.watchdog.trip`` chaos event; otherwise returns
        ``fn()``'s value (exceptions propagate unchanged)."""
        chaos = self.chaos
        tripped = (
            self.chaos_check()
            if self.chaos_check is not None
            else chaos is not None and chaos.due("consensus.watchdog.trip")
        )
        if tripped:
            # chaos[consensus.watchdog.trip] (or the reuser's probe):
            # the collective is wedged — the fetch would never return.
            # Report expiry without consuming the future (a dead
            # world's future has no value).
            self._trip(what, 0.0)
            raise CollectiveTimeout(
                f"chaos: {what} fetch treated as wedged (deterministic "
                "watchdog trip)"
            )
        if self.timeout <= 0:
            return fn()
        q = self._ensure_worker()
        box: dict = {"val": None, "err": None}
        done = threading.Event()
        q.put((fn, box, done))
        if not done.wait(self.timeout):
            self._abandon_worker()
            self._trip(what, self.timeout)
            raise CollectiveTimeout(
                f"{what} future missed the {self.timeout}s collective "
                "watchdog deadline (wedged allreduce? peer stood down "
                "without agreement?)"
            )
        if box["err"] is not None:
            raise box["err"]
        return box["val"]
