"""The step bus: a per-step int32 control word folded into the
training world's collectives.

Each step, every member contributes one 4-lane int32 word; the words
are allgathered over the SAME mesh (and therefore the same
``jax.distributed`` process group / gloo transport) as the train step,
so the bus inherits the data plane's synchronization for free: a
member cannot fall a step boundary behind the bus without falling
behind the model collectives too, and a wedged peer wedges the bus
exactly where the watchdog is looking.

Lanes:

- ``LANE_GENERATION``: highest coordinator plan generation this member
  has SEEN (polled, or learned from a peer via this very lane) — a
  member whose plan poll is delayed still learns a resize is wanted at
  the same step boundary as everyone else.
- ``LANE_STOP``: stop vote / agreement echo.  A member that observed a
  retarget proposes ``dispatch_step + agreement_horizon``; the FIRST
  harvested word with a nonzero stop lane defines the agreement (its
  max), which is >= every member's dispatch frontier + 1 by
  construction (horizon = pipeline_depth + 1), so nobody has run ahead
  of the boundary when it is learned.
- ``LANE_HEALTH``: poison bit — a member that knows it is failing
  (corrupt store, tripped watchdog) marks the word so peers bury the
  world proactively instead of discovering the failure as a hang.
- ``LANE_TIMING``: log2 bucket of the member's last step seconds — the
  free per-member straggler signal.

The gather is one tiny jit (input sharded one row per device, output
replicated); it is AOT-warmable from abstract shapes exactly like the
train step (``warm``), so a warm resize still performs ZERO XLA
compiles with the bus on.  The gathered word is a device future: the
elastic runtime harvests it with the same lag as the step metrics, so
the bus adds no per-step host<->device sync.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

#: word width; see the lane docs above
BUS_LANES = 4
LANE_GENERATION = 0
LANE_STOP = 1
LANE_HEALTH = 2
LANE_TIMING = 3

#: timing-lane quantization: bucket 0 is <= BUCKET0_SECONDS, each
#: bucket doubles; MAX_BUCKET caps pathological stalls
BUCKET0_SECONDS = 0.001
MAX_BUCKET = 31

#: buckets of spread between the slowest and fastest member before the
#: slowest is counted as a straggler (4 buckets = ~16x the fastest)
STRAGGLER_SPREAD_BUCKETS = 4


def timing_bucket(seconds: float) -> int:
    """Quantize a step duration into the word's log2 timing lane."""
    if seconds <= BUCKET0_SECONDS:
        return 0
    return min(MAX_BUCKET, int(math.log2(seconds / BUCKET0_SECONDS)) + 1)


class BusPoisonError(RuntimeError):
    """A peer marked the word's health lane: some member of this world
    knows it is failing.  Raised at harvest so the shared broken-world
    recovery path (``_absorb_step_failure``) buries the world before
    the failure surfaces as an untimed hang."""


@dataclass
class BusWord:
    """One decoded (harvested) control word."""

    step: int
    max_generation: int
    #: 0 = no stop voted/agreed in this word
    stop_step: int
    poisoned: bool
    #: process rank -> timing bucket (max over the rank's devices)
    member_buckets: Dict[int, int]
    #: bucket spread between slowest and fastest member
    skew: int
    #: rank of the slowest member when it qualifies as a straggler
    straggler: Optional[int] = None


@dataclass
class _Binding:
    """Per-mesh dispatch state: sharding, row ownership, executables."""

    mesh: Any
    in_sharding: Any
    n_rows: int
    row_owner: tuple
    jitted: Any
    compiled: Any = None


class StepBus:
    """Dispatch/decode the control word over a mesh.

    Bindings are cached per mesh (the elastic runtime returns to
    previously-seen world sizes constantly); ``clear()`` drops them
    when the device objects die (multipod world re-formation)."""

    def __init__(self, registry=None, recorder=None):
        from edl_tpu import telemetry

        self.registry = registry if registry is not None else telemetry.get_registry()
        self.recorder = recorder if recorder is not None else telemetry.get_recorder()
        self._m_words = self.registry.counter("edl_consensus_words_total")
        self._m_votes = self.registry.counter("edl_consensus_votes_total")
        self._g_stop = self.registry.gauge("edl_consensus_stop_step")
        self._g_skew = self.registry.gauge("edl_consensus_step_skew_buckets")
        self._m_stragglers = self.registry.counter(
            "edl_consensus_stragglers_total"
        )
        #: guards the binding cache against the background AOT prewarm
        #: threads racing the step loop (a _Binding keeps a strong ref
        #: to its mesh, so the id() key cannot be recycled while the
        #: binding lives)
        self._lock = threading.Lock()
        self._bindings: Dict[int, _Binding] = {}
        self._last_straggler: Optional[int] = None

    # -- binding -------------------------------------------------------------
    def bind(self, mesh) -> _Binding:
        with self._lock:
            b = self._bindings.get(id(mesh))
        if b is not None:
            return b
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        flat = list(mesh.devices.flatten())
        axes = tuple(mesh.axis_names)
        lead = axes if len(axes) > 1 else axes[0]
        in_sharding = NamedSharding(mesh, P(lead, None))
        out_sharding = NamedSharding(mesh, P())
        # Identity with a sharded->replicated reshard: XLA lowers it to
        # the world's allgather — the one collective the bus needs.
        jitted = jax.jit(lambda w: w, out_shardings=out_sharding)
        b = _Binding(
            mesh=mesh,
            in_sharding=in_sharding,
            n_rows=len(flat),
            row_owner=tuple(
                int(getattr(d, "process_index", 0)) for d in flat
            ),
            jitted=jitted,
        )
        with self._lock:
            return self._bindings.setdefault(id(mesh), b)

    def warm(self, mesh) -> bool:
        """AOT-compile the gather for ``mesh`` from abstract shapes
        (zero device allocation) and HOLD the executable — on this jax
        ``.lower().compile()`` does not warm the jit dispatch cache, so
        holding it is what keeps a warm resize at zero compiles (the
        same contract as ``Trainer.warm_step``)."""
        import jax

        b = self.bind(mesh)
        if b.compiled is not None:
            return False
        abstract = jax.ShapeDtypeStruct(
            (b.n_rows, BUS_LANES), np.int32, sharding=b.in_sharding
        )
        with mesh:
            b.compiled = b.jitted.lower(abstract).compile()
        return True

    def clear(self) -> None:
        """Drop every mesh binding (the device objects are dying —
        multipod world teardown)."""
        with self._lock:
            self._bindings.clear()
        self._last_straggler = None

    # -- dispatch ------------------------------------------------------------
    def dispatch(
        self,
        mesh,
        step: int,
        generation: int,
        stop: int,
        poison: bool,
        bucket: int,
    ):
        """Place this member's word and dispatch the allgather.
        Returns the gathered word as a DEVICE FUTURE — no host sync;
        the caller harvests it with the step-metrics lag."""
        import jax

        b = self.bind(mesh)
        row = np.array(
            [[generation, stop, 1 if poison else 0, bucket]], np.int32
        )
        arr = jax.make_array_from_callback(
            (b.n_rows, BUS_LANES), b.in_sharding, lambda idx: row
        )
        with mesh:
            if b.compiled is not None:
                return b.compiled(arr)
            return b.jitted(arr)

    # -- decode --------------------------------------------------------------
    def decode(self, mesh, step: int, mat: np.ndarray) -> BusWord:
        """Decode a harvested (already host-materialized) word matrix
        and publish its telemetry.  Deterministic: every member decodes
        the identical gathered matrix, so agreement needs no further
        communication."""
        b = self.bind(mesh)
        buckets: Dict[int, int] = {}
        for rank, bk in zip(b.row_owner, mat[:, LANE_TIMING]):
            buckets[rank] = max(buckets.get(rank, 0), int(bk))
        skew = (max(buckets.values()) - min(buckets.values())) if buckets else 0
        straggler = None
        if len(buckets) > 1 and skew >= STRAGGLER_SPREAD_BUCKETS:
            straggler = max(buckets, key=buckets.get)
        word = BusWord(
            step=step,
            max_generation=int(mat[:, LANE_GENERATION].max()),
            stop_step=int(mat[:, LANE_STOP].max()),
            poisoned=bool(mat[:, LANE_HEALTH].max() > 0),
            member_buckets=buckets,
            skew=skew,
            straggler=straggler,
        )
        self._m_words.inc()
        self._g_skew.set(skew)
        if straggler is not None:
            self._m_stragglers.inc(rank=str(straggler))
            if straggler != self._last_straggler:
                # Journal transitions only: a persistent straggler must
                # not flood the flight-recorder ring once per step.
                self.recorder.record(
                    "consensus.straggler",
                    {
                        "rank": straggler,
                        "skew_buckets": skew,
                        "buckets": {
                            str(r): v for r, v in sorted(buckets.items())
                        },
                    },
                    step=step,
                )
        self._last_straggler = straggler
        return word

    # -- agreement accounting ------------------------------------------------
    def note_vote(self, step: int, generation: int, proposed_stop: int) -> None:
        self._m_votes.inc()
        self.recorder.record(
            "consensus.vote",
            {"proposed_stop": proposed_stop, "for_generation": generation},
            step=step,
        )

    def note_stop(self, vote_step: int, stop_step: int, generation: int) -> None:
        self._g_stop.set(stop_step)
        self.recorder.record(
            "consensus.stop",
            {
                "vote_step": vote_step,
                "stop_step": stop_step,
                "for_generation": generation,
            },
            step=vote_step,
        )
