"""Data-plane step agreement: the protocol behind deadlock-free
scale-down.

EDL's contract is that scaling can hit the job at any time, but until
this subsystem members quiesced by *polling* the coordinator plan: at a
retarget one member could observe the new plan a step boundary before
its peer and stand down while the peer's already-dispatched collective
waited for it forever (the measured 2/5 hang of
``test_multipod_elastic_1_2_1`` — shutdown barrier vs gloo allreduce,
neither with a timeout).  Varuna solves exactly this with a "morph"
signal agreed over the data plane so every worker leaves at the same
step, and Bamboo shows preemption-tolerant training needs an in-band
agreement path plus a watchdog rather than trusting the control
plane's timing (PAPERS.md).

Three pieces:

- ``StepBus``: a tiny int32 control word allgathered over the SAME
  ``jax.distributed`` world as the model step — every member learns at
  the same step boundary that a resize is wanted, and all agree on
  ``stop_step = vote_step + agreement_horizon`` (horizon =
  ``pipeline_depth + 1``, so the async pipeline keeps its zero per-step
  host syncs: the word is a device future harvested with the existing
  lag, and run-ahead dispatch is clamped at the agreed stop step).
- ``CollectiveWatchdog``: a deadline on in-flight step/control futures
  so a wedged gloo allreduce (no native timeout) is detected and buried
  via the shared broken-world recovery path instead of hanging the
  world.
- Straggler telemetry: the word's timing lane gives per-member
  step-skew without any extra traffic.
"""

from edl_tpu.consensus.bus import (
    BUS_LANES,
    BusPoisonError,
    BusWord,
    StepBus,
    timing_bucket,
)
from edl_tpu.consensus.watchdog import CollectiveTimeout, CollectiveWatchdog

__all__ = [
    "BUS_LANES",
    "BusPoisonError",
    "BusWord",
    "StepBus",
    "timing_bucket",
    "CollectiveTimeout",
    "CollectiveWatchdog",
]
