"""Structural regression gates over a bench round record.

ROADMAP item 5's "make regressions structural": the per-section
invariants the repo's perf story rests on — scale-down stop-step skew
== 0, serving steady-state XLA compiles == 0, warm-resize compiles ==
0 (already bench-asserted in-section; re-gated here so a silently
error'd section can't pass), latency ceilings — are asserted by CI
against a checked-in thresholds JSON, normally over the committed
``BENCH_r*.json`` snapshot (so a snapshot that violates its own gates
can never be the baseline) and, when ``EDL_BENCH_RECORD`` points at a
fresh ``bench.py`` output, over that.

Stdlib-only, like tools/lint.py.  Threshold schema (a JSON list):

    {"path": "detail.scale_down.stop_skew_steps", "max": 0}
    {"path": "detail.fleet.slo_attainment", "min": 1.0}
    {"path": "detail.steady_state.mnist.losses_bit_identical",
     "equals": true}
    {"path": "detail.moe_lm.mfu", "min": 0.3, "required": false}

``required`` defaults to true: a missing path (section error'd, key
renamed) FAILS the gate — a gate that silently stops measuring is the
regression class this tool exists for.  ``required: false`` marks
platform-dependent sections (TPU-only models skip on CPU boxes).
"""

from __future__ import annotations

import argparse
import json
import sys


def resolve(doc, path: str):
    """Dotted-path lookup; returns (found, value)."""
    cur = doc
    for part in path.split("."):
        if isinstance(cur, list):
            try:
                cur = cur[int(part)]
                continue
            except (ValueError, IndexError):
                return False, None
        if not isinstance(cur, dict) or part not in cur:
            return False, None
        cur = cur[part]
    return True, cur


def check(record: dict, gates: list) -> list:
    """Returns a list of failure strings (empty = all gates green)."""
    failures = []
    for gate in gates:
        path = gate["path"]
        required = gate.get("required", True)
        found, value = resolve(record, path)
        if not found:
            if required:
                failures.append(
                    f"{path}: MISSING (section error'd or key renamed; "
                    "a gate that stopped measuring is a failure)"
                )
            else:
                print(f"  skip  {path} (absent, optional)")
            continue
        ok = True
        why = []
        if "equals" in gate and value != gate["equals"]:
            ok = False
            why.append(f"!= {gate['equals']!r}")
        if "max" in gate:
            if not isinstance(value, (int, float)) or value > gate["max"]:
                ok = False
                why.append(f"> max {gate['max']}")
        if "min" in gate:
            if not isinstance(value, (int, float)) or value < gate["min"]:
                ok = False
                why.append(f"< min {gate['min']}")
        if ok:
            print(f"  ok    {path} = {value!r}")
        else:
            failures.append(f"{path} = {value!r} ({', '.join(why)})")
    return failures


def load_record(path: str) -> dict:
    """Accept either bench.py's raw one-line record or the round
    driver's wrapper (which nests it under ``parsed``)."""
    with open(path) as f:
        doc = json.load(f)
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    if "detail" not in doc:
        raise SystemExit(
            f"{path}: not a bench round record (no 'detail' key)"
        )
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("record", help="bench round record JSON")
    ap.add_argument(
        "--thresholds",
        default="bench_thresholds.json",
        help="checked-in per-section gate definitions",
    )
    args = ap.parse_args(argv)
    record = load_record(args.record)
    with open(args.thresholds) as f:
        spec = json.load(f)
    gates = spec["gates"] if isinstance(spec, dict) else spec
    print(f"bench gates: {args.record} vs {args.thresholds}")
    failures = check(record, gates)
    if failures:
        print(f"\nbench gates FAILED ({len(failures)}):", file=sys.stderr)
        for f_ in failures:
            print(f"  FAIL  {f_}", file=sys.stderr)
        return 1
    print(f"bench gates: clean ({len(gates)} gates)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
