#!/usr/bin/env python
"""Zero-dependency lint gate (the reference gated commits on
gofmt/govet/golint via pre-commit, ``.travis.yml:10-18`` +
``.pre-commit-config.yaml``; this is the Python analog for an image
with no linters installed and installs forbidden).

Checks, all stdlib:

- syntax (ast.parse)
- unused imports (module-scope imports never referenced)
- bare ``except:`` (masks KeyboardInterrupt/SystemExit)
- debugger leftovers (``breakpoint()``, ``pdb.set_trace``)
- mutable default arguments (list/dict/set literals)
- f-strings with no placeholders
- tabs in indentation, trailing whitespace, overlong lines (> MAX_LINE)
- unregistered metric names: every ``.counter("...")`` /
  ``.gauge("...")`` / ``.histogram("...")`` call site (outside tests/)
  must name a metric declared in ``edl_tpu/telemetry/catalog.py``, and
  the name must be a string LITERAL — free-form/computed names defeat
  the catalog and are rejected outright
- unregistered chaos injection points: every ``.due("...")`` /
  ``.maybe_raise("...")`` / ``.roll("...")`` / ``.rng("...")`` call
  site (outside tests/ and the registry module itself) must name a
  point declared in ``edl_tpu/chaos/schedule.py``'s ``KNOWN_POINTS``
  — a typo'd point would otherwise silently never fire, turning a
  chaos test into a vacuous pass
- unregistered flight-event kinds: every ``.record("...")`` call site
  (outside tests/ and the recorder module itself, whose ingest path
  legitimately passes computed kinds) must name an entry in
  ``edl_tpu/telemetry/catalog.py``'s ``KNOWN_EVENT_KINDS`` — free-form
  kinds are what make merged cluster timelines unreadable
- blocking device fetches in the elastic hot loop: ``float(...)``,
  ``int(...)`` and ``.item()`` calls inside ``ElasticTrainer.run`` are
  rejected — the async step pipeline keeps metrics as device futures
  and syncs only at the sanctioned sync points (the harvest path), so
  a per-step host<->device round trip cannot silently regress.  A
  deliberate sync marks its line ``# sanctioned-sync``.

Exit code 1 on any finding — ``ci.sh`` runs this before the tests.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 100

#: names whose import is a re-export or side-effect, not a use
REEXPORT_FILES = {"__init__.py"}

#: registry handle constructors whose first argument is a metric name
METRIC_METHODS = {"counter", "gauge", "histogram"}

#: FaultSchedule methods whose first argument is an injection-point
#: name (the chaos analog of METRIC_METHODS)
CHAOS_METHODS = {"due", "maybe_raise", "roll", "rng"}

#: the chaos registry module — its own internals legitimately pass
#: computed point names (event delivery iterates the schedule)
CHAOS_REGISTRY = ("edl_tpu", "chaos", "schedule.py")

#: FlightRecorder methods whose first argument is an event kind
EVENT_METHODS = {"record"}

#: the recorder module itself — ``ingest`` re-records already
#: serialized events under their (computed) original kinds
EVENT_REGISTRY = ("edl_tpu", "telemetry", "recorder.py")

#: (class, methods) whose bodies form the elastic hot loop: blocking
#: device fetches are banned there (see _hot_loop_findings)
HOT_LOOP_CLASS = "ElasticTrainer"
HOT_LOOP_METHODS = {"run"}

#: line marker that sanctions a deliberate device sync in the hot loop
SYNC_MARKER = "# sanctioned-sync"

#: builtins whose call on a jax array blocks on device completion
BLOCKING_CASTS = {"float", "int"}

_CATALOG_CACHE = [False, None]  # [loaded, names-or-None]
_CHAOS_CACHE = [False, None]  # [loaded, points-or-None]
_KINDS_CACHE = [False, None]  # [loaded, kinds-or-None]


def _literal_from(path: Path, var: str):
    """The set of keys/items of a module-level pure-literal assignment
    named ``var`` in ``path``; None when absent/unparseable."""
    try:
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == var:
                        return set(ast.literal_eval(node.value))
    except (OSError, SyntaxError, ValueError):
        pass
    return None


def _event_kind_registry():
    """Event kinds declared in edl_tpu/telemetry/catalog.py's
    KNOWN_EVENT_KINDS (a pure dict literal; the set of its keys).
    None when absent/unparseable — the check then degrades to
    literal-ness only."""
    if not _KINDS_CACHE[0]:
        _KINDS_CACHE[0] = True
        _KINDS_CACHE[1] = _literal_from(
            Path(__file__).resolve().parent.parent
            / "edl_tpu"
            / "telemetry"
            / "catalog.py",
            "KNOWN_EVENT_KINDS",
        )
    return _KINDS_CACHE[1]


def _event_kind_findings(tree: ast.AST, path: Path):
    """Reject unregistered / free-form flight-event kinds — the third
    leg of the catalog-strict family (metrics, chaos points, event
    kinds).  Free-form kinds don't fail at runtime; they just turn the
    merged timeline into an accretion of strings nobody can lane."""
    if "tests" in path.parts or path.parts[-3:] == EVENT_REGISTRY:
        return
    registry = _event_kind_registry()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (
            isinstance(f, ast.Attribute) and f.attr in EVENT_METHODS
        ):
            continue
        if not node.args:
            continue
        a = node.args[0]
        if not (isinstance(a, ast.Constant) and isinstance(a.value, str)):
            if isinstance(a, ast.Constant):
                continue  # not an event kind (e.g. some .record(5))
            yield node.lineno, (
                f"free-form event kind passed to .{f.attr}() — flight-"
                "event kinds must be string literals from "
                "telemetry/catalog.py KNOWN_EVENT_KINDS"
            )
            continue
        if registry is not None and a.value not in registry:
            yield node.lineno, (
                f"unregistered flight-event kind {a.value!r} — declare "
                "it in edl_tpu/telemetry/catalog.py KNOWN_EVENT_KINDS"
            )


def _metric_catalog():
    """Metric names declared in edl_tpu/telemetry/catalog.py, parsed
    statically (the catalog is a pure literal precisely so this gate
    needs no imports).  None when the catalog is absent/unparseable —
    the check then degrades to literal-ness only."""
    if not _CATALOG_CACHE[0]:
        _CATALOG_CACHE[0] = True
        _CATALOG_CACHE[1] = _literal_from(
            Path(__file__).resolve().parent.parent
            / "edl_tpu"
            / "telemetry"
            / "catalog.py",
            "CATALOG",
        )
    return _CATALOG_CACHE[1]


def _chaos_registry():
    """Injection points declared in edl_tpu/chaos/schedule.py's
    KNOWN_POINTS, parsed statically (the registry is a pure tuple
    literal for exactly this reason).  None when absent/unparseable —
    the check then degrades to literal-ness only."""
    if not _CHAOS_CACHE[0]:
        _CHAOS_CACHE[0] = True
        _CHAOS_CACHE[1] = _literal_from(
            Path(__file__).resolve().parent.parent.joinpath(
                *CHAOS_REGISTRY
            ),
            "KNOWN_POINTS",
        )
    return _CHAOS_CACHE[1]


def _chaos_point_findings(tree: ast.AST, path: Path):
    """Reject unregistered / free-form chaos injection-point names —
    the mirror of the catalog-strict metrics check.  A typo'd point
    would silently never fire (``due`` just matches nothing), so the
    chaos test guarding a recovery path would pass vacuously.  Tests
    and the registry module itself are excluded (tests exercise
    unknown-point rejection on purpose; the registry's delivery loop
    passes computed names)."""
    if "tests" in path.parts or path.parts[-3:] == CHAOS_REGISTRY:
        return
    registry = _chaos_registry()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (
            isinstance(f, ast.Attribute) and f.attr in CHAOS_METHODS
        ):
            continue
        if not node.args:
            continue
        a = node.args[0]
        if not (isinstance(a, ast.Constant) and isinstance(a.value, str)):
            if isinstance(a, ast.Constant):
                continue  # not a chaos point (e.g. some .due(3))
            yield node.lineno, (
                f"free-form chaos point passed to .{f.attr}() — "
                "injection points must be string literals from "
                "chaos/schedule.py KNOWN_POINTS"
            )
            continue
        if registry is not None and a.value not in registry:
            yield node.lineno, (
                f"unregistered chaos injection point {a.value!r} — "
                "declare it in edl_tpu/chaos/schedule.py KNOWN_POINTS"
            )


def _metric_name_findings(tree: ast.AST, path: Path):
    """Reject unregistered / free-form metric names (tests excluded:
    they may exercise non-strict registries on purpose)."""
    if "tests" in path.parts:
        return
    catalog = _metric_catalog()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (
            isinstance(f, ast.Attribute) and f.attr in METRIC_METHODS
        ):
            continue
        if not node.args:
            continue
        a = node.args[0]
        if not (isinstance(a, ast.Constant) and isinstance(a.value, str)):
            if isinstance(a, ast.Constant):
                continue  # e.g. collections.Counter(5) — not a metric
            yield node.lineno, (
                f"free-form metric name passed to .{f.attr}() — metric "
                "names must be string literals from the catalog"
            )
            continue
        if catalog is not None and a.value not in catalog:
            yield node.lineno, (
                f"unregistered metric name {a.value!r} — declare it in "
                "edl_tpu/telemetry/catalog.py"
            )


def _hot_loop_findings(tree: ast.AST, path: Path, sanctioned: set):
    """Reject blocking device fetches in the elastic hot loop.  Scoped
    to ``ElasticTrainer``'s step-loop methods wherever they are
    defined: ``float()``/``int()``/``.item()`` there forces a
    host<->device round trip per step — exactly the per-step sync the
    async pipeline retired.  ``sanctioned`` holds line numbers carrying
    the SYNC_MARKER comment (deliberate, reviewed syncs)."""
    if "tests" in path.parts:
        return
    for cls in ast.walk(tree):
        if not (
            isinstance(cls, ast.ClassDef) and cls.name == HOT_LOOP_CLASS
        ):
            continue
        for fn in cls.body:
            if not (
                isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name in HOT_LOOP_METHODS
            ):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if node.lineno in sanctioned:
                    continue
                f = node.func
                if (
                    isinstance(f, ast.Name)
                    and f.id in BLOCKING_CASTS
                    and node.args
                ):
                    yield node.lineno, (
                        f"blocking device fetch {f.id}(...) in "
                        f"{HOT_LOOP_CLASS}.{fn.name}'s hot path — keep "
                        "metrics as device futures and harvest at a "
                        "sanctioned sync point (or mark the line "
                        f"{SYNC_MARKER!r})"
                    )
                elif isinstance(f, ast.Attribute) and f.attr == "item":
                    yield node.lineno, (
                        f"blocking device fetch .item() in "
                        f"{HOT_LOOP_CLASS}.{fn.name}'s hot path — keep "
                        "metrics as device futures and harvest at a "
                        "sanctioned sync point (or mark the line "
                        f"{SYNC_MARKER!r})"
                    )


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # the ROOT of a dotted use: jax.numpy -> jax
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    # names referenced in __all__ string literals count as used
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for el in ast.walk(node.value):
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, str
                        ):
                            used.add(el.value)
    return used


def _unused_imports(tree: ast.AST, path: Path):
    if path.name in REEXPORT_FILES:
        return
    used = _used_names(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                if name not in used:
                    yield node.lineno, f"unused import {a.name!r}"
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                if name not in used:
                    yield node.lineno, f"unused import {name!r}"


def _ast_findings(tree: ast.AST, path: Path, sanctioned: set = frozenset()):
    yield from _unused_imports(tree, path)
    yield from _metric_name_findings(tree, path)
    yield from _chaos_point_findings(tree, path)
    yield from _event_kind_findings(tree, path)
    yield from _hot_loop_findings(tree, path, sanctioned)
    # f-string format specs are themselves JoinedStr nodes with no
    # FormattedValue (f"{x:02d}" nests JoinedStr(['02d'])): exclude
    # them from the no-placeholder check or every formatted f-string
    # false-positives.
    spec_ids = {
        id(node.format_spec)
        for node in ast.walk(tree)
        if isinstance(node, ast.FormattedValue) and node.format_spec
    }
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield node.lineno, "bare except: (catches SystemExit too)"
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "breakpoint":
                yield node.lineno, "breakpoint() left in"
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "set_trace"
                and isinstance(f.value, ast.Name)
                and f.value.id in ("pdb", "ipdb")
            ):
                yield node.lineno, "pdb.set_trace() left in"
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    yield d.lineno, (
                        f"mutable default argument in {node.name}()"
                    )
        if isinstance(node, ast.JoinedStr) and id(node) not in spec_ids:
            if not any(
                isinstance(v, ast.FormattedValue) for v in node.values
            ):
                yield node.lineno, "f-string without placeholders"


def _line_findings(text: str):
    for i, line in enumerate(text.splitlines(), 1):
        body = line.rstrip("\n")
        if body != body.rstrip():
            yield i, "trailing whitespace"
        indent = body[: len(body) - len(body.lstrip())]
        if "\t" in indent:
            yield i, "tab in indentation"
        if len(body) > MAX_LINE:
            yield i, f"line too long ({len(body)} > {MAX_LINE})"


def lint_file(path: Path):
    text = path.read_text()
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        yield e.lineno or 0, f"syntax error: {e.msg}"
        return
    # standard suppression: a `# noqa` comment on the flagged line
    lines = text.splitlines()
    noqa = {
        i for i, line in enumerate(lines, 1) if "# noqa" in line
    }
    sanctioned = {
        i for i, line in enumerate(lines, 1) if SYNC_MARKER in line
    }
    for lineno, msg in _ast_findings(tree, path, sanctioned):
        if lineno not in noqa:
            yield lineno, msg
    for lineno, msg in _line_findings(text):
        if lineno not in noqa:
            yield lineno, msg


def main(argv) -> int:
    roots = [Path(p) for p in argv] or [
        Path("edl_tpu"),
        Path("tests"),
        Path("tools"),
        Path("bench.py"),
        Path("__graft_entry__.py"),
    ]
    files = []
    for root in roots:
        if root.is_dir():
            files += sorted(root.rglob("*.py"))
        elif root.suffix == ".py":
            files.append(root)
    findings = 0
    for f in files:
        for lineno, msg in lint_file(f):
            print(f"{f}:{lineno}: {msg}")
            findings += 1
    if findings:
        print(f"lint: {findings} finding(s) in {len(files)} files")
        return 1
    print(f"lint: clean ({len(files)} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
