"""Benchmark driver: prints ONE JSON line for the round record.

Headline metric: **elastic resize latency** — seconds from "resize
requested" to "stepping again on the new mesh" (checkpoint flush ->
re-mesh -> restore -> first step).  This is the north-star number in
BASELINE.md: the reference publishes no benchmarks (SURVEY.md §6), so
the target is the <60s re-converge budget from BASELINE.json.
``vs_baseline`` = 60 / measured_seconds: 1.0 is exactly on budget,
>1 is that many times faster than budget.

Runs on whatever accelerator jax finds (the driver provides one real
TPU chip); world sizes cycle over the available devices the same way
the elastic runtime does in production.
"""

from __future__ import annotations

import json
import statistics


RESIZE_BUDGET_S = 60.0


def bench_resize(model_name: str = "mnist", steps_per_phase: int = 10) -> dict:
    import jax
    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer

    devices = jax.devices()
    n_dev = len(devices)
    sizes = sorted({1, max(1, n_dev // 2), n_dev})

    model = get_model(model_name)
    data = ShardedDataIterator(
        synthetic_dataset(model.synth_batch, 4096),
        global_batch_size=max(64, 8 * n_dev),
    )
    coord = LocalCoordinator(target_world=1, max_world=n_dev)
    for i in range(n_dev):
        coord.register(f"t{i}")
    et = ElasticTrainer(
        model,
        optax.sgd(0.05),
        data,
        coord,
        devices=devices,
        checkpoint_interval=5,
    )
    # Warm the compiled-step cache for every size so the measured window
    # is the true resize path, not first-compile (production pre-compiles
    # per legal mesh size; SURVEY.md §7.4).
    et.precompile(sizes)
    target = steps_per_phase
    et.run(target)

    resize_windows = []
    step_times = []
    # Cycle up then down through world sizes (e.g. 1 -> 4 -> 8 -> 4 -> 1).
    # On a single chip every entry is 1: the resize is then forced via
    # membership churn (leave+rejoin), which runs the identical barrier.
    cycle = (sizes[1:] + sizes[:-1][::-1]) or [1, 1, 1]
    prev_w = sizes[0]
    for w in cycle:
        if w == prev_w:
            coord.deregister(f"t{w - 1}")
            coord.register(f"t{w - 1}")
        else:
            coord.set_target_world(w)
        prev_w = w
        et.maybe_resize()
        target += steps_per_phase
        et.run(target)
        gen = et.generation
        first = next(r for r in et.history if r.generation == gen)
        # Window = resize barrier (event.seconds) + first post-resize step.
        event = et.resize_events[-1]
        assert event.generation == gen
        resize_windows.append(event.seconds + first.seconds)
        step_times.extend(r.seconds for r in et.history[-3:])

    # Join any in-flight async checkpoint thread before teardown (a live
    # device->host copy racing interpreter exit aborts the TPU runtime).
    et.store.wait()

    return {
        "resize_s": statistics.median(resize_windows),
        "resize_max_s": max(resize_windows),
        "step_s": statistics.median(step_times),
        "n_devices": n_dev,
        "world_cycle": cycle,
    }


def main():
    r = bench_resize()
    value = round(r["resize_s"], 4)
    print(
        json.dumps(
            {
                "metric": "elastic_resize_latency",
                "value": value,
                "unit": "s",
                "vs_baseline": round(RESIZE_BUDGET_S / max(value, 1e-9), 2),
                "detail": {
                    "resize_max_s": round(r["resize_max_s"], 4),
                    "median_step_s": round(r["step_s"], 5),
                    "n_devices": r["n_devices"],
                    "world_cycle": r["world_cycle"],
                    "budget_s": RESIZE_BUDGET_S,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
