"""Benchmark driver: prints ONE JSON line for the round record.

Headline metric: **elastic resize latency** — seconds from "resize
requested" to "stepping again on the new mesh" (checkpoint flush ->
re-mesh -> restore -> first step).  This is the north-star number in
BASELINE.md: the reference publishes no benchmarks (SURVEY.md §6), so
the target is the <60s re-converge budget from BASELINE.json.
``vs_baseline`` = 60 / measured_seconds: 1.0 is exactly on budget,
>1 is that many times faster than budget.

Runs on whatever accelerator jax finds (the driver provides one real
TPU chip); world sizes cycle over the available devices the same way
the elastic runtime does in production.

Every section lives in its own ``bench_lib/`` module (ROADMAP item
5's per-module split, completed this round with resize, scale_down
and the LM family): this file only composes sections into the one
record.  Heavy sections spawn their own hermetic children via
``python -m bench_lib.<module>``, so the driver never initializes a
TPU client before the chip-exclusive LM children run.
"""

from __future__ import annotations

import json
import sys

from bench_lib.resize import RESIZE_BUDGET_S


def bench_resize(model_name: str = "mnist", steps_per_phase: int = 10) -> dict:
    from bench_lib.resize import bench_resize as _bench_resize

    return _bench_resize(model_name=model_name, steps_per_phase=steps_per_phase)


def bench_cpu_cross_size(n_devices: int = 8) -> dict:
    from bench_lib.resize import bench_cpu_cross_size as _bench_cross

    return _bench_cross(n_devices=n_devices)


def bench_transformer_throughput(steps: int = 20) -> dict:
    from bench_lib.lm import bench_transformer_throughput as _bench_thr

    return _bench_thr(steps=steps)


def bench_mnist_throughput(steps: int = 20) -> dict:
    from bench_lib.lm import bench_mnist_throughput as _bench_mnist

    return _bench_mnist(steps=steps)


def bench_longcontext_lm(seq_len: int = 2048, batch: int = 8, steps: int = 8) -> dict:
    from bench_lib.lm import bench_longcontext_lm as _bench_lc

    return _bench_lc(seq_len=seq_len, batch=batch, steps=steps)


def bench_moe_lm(batch: int = 8, steps: int = 8, group: int = 0) -> dict:
    from bench_lib.lm import bench_moe_lm as _bench_moe

    return _bench_moe(batch=batch, steps=steps, group=group)


def bench_serving() -> dict:
    from bench_lib.serving import bench_serving as _bench_serving

    return _bench_serving()


def bench_fleet() -> dict:
    from bench_lib.fleet import bench_fleet as _bench_fleet

    return _bench_fleet()


def bench_router() -> dict:
    from bench_lib.router import bench_router as _bench_router

    return _bench_router()


def bench_steady_state(steps: int = 30) -> dict:
    from bench_lib.steady_state import bench_steady_state as _bench_ss

    return _bench_ss(steps=steps)


def bench_restore_paths() -> dict:
    from bench_lib.restore import run_restore_paths

    return run_restore_paths()


def bench_shard_only_restore() -> dict:
    from bench_lib.restore import run_shard_only

    return run_shard_only()


def bench_shard_only_restore_k2() -> dict:
    from bench_lib.restore import run_shard_only

    return run_shard_only(k=2)


def bench_scale_down() -> dict:
    from bench_lib.scale_down import bench_scale_down as _bench_sd

    return _bench_sd()


def _attempt(fn, label: str, retries: int = 1):
    """Run a bench section; on failure print the traceback to stderr and
    return an ``{"error": ...}`` dict instead of silently dropping data.
    One retry absorbs transient platform flakes (e.g. a mid-flight libtpu
    upgrade on the tunneled device) without hiding persistent failures."""
    import traceback

    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:
            print(f"[bench] {label} attempt {attempt + 1} failed:", file=sys.stderr)
            traceback.print_exc()
            err = f"{type(e).__name__}: {e}"
    return {"error": err[:500]}


def _platform() -> str:
    import jax

    return jax.default_backend()


def main():
    from bench_lib.lm import lm_summary

    # Long-context first: its child must own the chip alone (this
    # process has not initialized a TPU client yet).
    lc = _attempt(bench_longcontext_lm, "longcontext_lm", retries=0)
    lc4k = _attempt(
        lambda: bench_longcontext_lm(seq_len=4096, batch=4, steps=4),
        "longcontext_lm_4k",
        retries=0,
    )
    # T=8192/16384 single-chip: r4's wall was T=4096 (the merged
    # backward overflowed the DEFAULT 16MB scoped-VMEM limit, and
    # nothing fit at 8k); the raised per-shape VMEM limit
    # (flash_attention._vmem_limit) runs the merged kernel clean to 16k.
    lc8k = _attempt(
        lambda: bench_longcontext_lm(seq_len=8192, batch=2, steps=4),
        "longcontext_lm_8k",
        retries=0,
    )
    lc16k = _attempt(
        lambda: bench_longcontext_lm(seq_len=16384, batch=1, steps=4),
        "longcontext_lm_16k",
        retries=0,
    )
    moe = _attempt(bench_moe_lm, "moe_lm", retries=0)
    r = _attempt(bench_resize, "resize")
    thr = _attempt(bench_transformer_throughput, "transformer_base")
    mnist = _attempt(bench_mnist_throughput, "mnist", retries=0)
    steady = _attempt(bench_steady_state, "steady_state", retries=0)
    cross = _attempt(bench_cpu_cross_size, "cpu_cross_size", retries=0)
    restore = _attempt(bench_restore_paths, "restore_paths", retries=0)
    shard_only = _attempt(
        bench_shard_only_restore, "restore_paths.shard_only", retries=0
    )
    shard_only_k2 = _attempt(
        bench_shard_only_restore_k2,
        "restore_paths.shard_only_k2",
        retries=0,
    )
    if isinstance(restore, dict):
        # shard_only rides inside restore_paths in the round record
        # (it is a restore-path figure), but is attempted separately so
        # a failure in one half does not drop the other.  The K=2 run
        # (ISSUE 20 satellite: K>1 rings measured, not just
        # layout-tested) rides beside the K=1 figure.
        restore = dict(restore)
        restore["shard_only"] = shard_only
        restore["shard_only_k2"] = shard_only_k2
    scale_down = _attempt(bench_scale_down, "scale_down", retries=0)
    serving = _attempt(bench_serving, "serving", retries=0)
    router = _attempt(bench_router, "serving.router", retries=0)
    if isinstance(serving, dict):
        # the front door rides inside the serving section (it IS a
        # serving figure), attempted separately so one half failing
        # does not drop the other.
        serving = dict(serving)
        serving["router"] = router
    fleet = _attempt(bench_fleet, "fleet", retries=0)
    if "error" in r:
        # The headline section itself died: emit an explicit error record
        # rather than nothing (the driver still gets one JSON line).
        print(
            json.dumps(
                {
                    "metric": "elastic_resize_latency",
                    "value": None,
                    "unit": "s",
                    "vs_baseline": None,
                    "detail": {"error": r["error"], "transformer_base": thr,
                               "longcontext_lm": lc,
                               "longcontext_lm_4k": lc4k,
                               "longcontext_lm_8k": lc8k,
                               "longcontext_lm_16k": lc16k, "moe_lm": moe,
                               "mnist": mnist,
                               "steady_state": steady,
                               "cpu_cross_size": cross,
                               "restore_paths": restore,
                               "scale_down": scale_down,
                               "serving": serving,
                               "fleet": fleet},
                }
            )
        )
        sys.exit(1)
    value = round(r["resize_s"], 4)
    print(
        json.dumps(
            {
                "metric": "elastic_resize_latency",
                "value": value,
                "unit": "s",
                "vs_baseline": round(RESIZE_BUDGET_S / max(value, 1e-9), 2),
                "detail": {
                    "resize_max_s": round(r["resize_max_s"], 4),
                    "median_step_s": round(r["step_s"], 5),
                    # default-on registry cost per step vs the median
                    # step (the < 1% acceptance bar of ISSUE 6)
                    "telemetry": r.get("telemetry", {}),
                    "n_devices": r["n_devices"],
                    "world_cycle": r["world_cycle"],
                    "resize_phases": r.get("resize_phases", {}),
                    "resize_events": r.get("resize_events", []),
                    "warm_resize_xla_compiles": r.get(
                        "warm_resize_xla_compiles"
                    ),
                    "budget_s": RESIZE_BUDGET_S,
                    # BASELINE config 1/2 throughput (VERDICT r5 #8)
                    "mnist": mnist,
                    # pipeline on/off A/B with per-step phase breakdown
                    "steady_state": steady,
                    "transformer_base": lm_summary(thr),
                    "longcontext_lm": lm_summary(lc),
                    "longcontext_lm_4k": lm_summary(lc4k),
                    "longcontext_lm_8k": lm_summary(lc8k),
                    "longcontext_lm_16k": lm_summary(lc16k),
                    "moe_lm": lm_summary(moe),
                    "cpu_cross_size": (
                        cross
                        if "error" in cross
                        else {
                            "resize_s": round(cross["resize_s"], 4),
                            "resize_max_s": round(cross["resize_max_s"], 4),
                            "n_devices": cross["n_devices"],
                            "world_cycle": cross["world_cycle"],
                            "resize_phases": cross.get("resize_phases", {}),
                            "resize_events": cross.get("resize_events", []),
                            "warm_resize_xla_compiles": cross.get(
                                "warm_resize_xla_compiles"
                            ),
                        }
                    ),
                    # joiner restore paths side by side + the fabric
                    # sweep + the shard-only cluster-memory figures
                    # (peak per-member RSS vs full-copy, joiner wire)
                    "restore_paths": restore,
                    # retarget->quiesce latency + stop-step skew
                    # (asserted 0) across a real 4->2 process world
                    "scale_down": scale_down,
                    # elastic inference serving: offered-load sweep
                    # (p50/p95/occupancy), 0-compile request path,
                    # hot-swap with zero failed/dropped requests,
                    # pre-warmed scale-up first request
                    "serving": serving,
                    # multi-job fleet market: scripted storm on real
                    # processes — spike -> consensus-clean preemption
                    # of the lowest-priority trainer -> recovery, with
                    # per-job goodput, chips-over-time, SLO attainment
                    "fleet": fleet,
                    # platform honesty: TPU rounds and CPU-box rounds
                    # must not be compared line to line
                    "platform": _platform(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
