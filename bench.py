"""Benchmark driver: prints ONE JSON line for the round record.

Headline metric: **elastic resize latency** — seconds from "resize
requested" to "stepping again on the new mesh" (checkpoint flush ->
re-mesh -> restore -> first step).  This is the north-star number in
BASELINE.md: the reference publishes no benchmarks (SURVEY.md §6), so
the target is the <60s re-converge budget from BASELINE.json.
``vs_baseline`` = 60 / measured_seconds: 1.0 is exactly on budget,
>1 is that many times faster than budget.

Runs on whatever accelerator jax finds (the driver provides one real
TPU chip); world sizes cycle over the available devices the same way
the elastic runtime does in production.
"""

from __future__ import annotations

import json
import statistics
import sys


RESIZE_BUDGET_S = 60.0


def bench_resize(model_name: str = "mnist", steps_per_phase: int = 10) -> dict:
    import jax
    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer

    devices = jax.devices()
    n_dev = len(devices)
    sizes = sorted({1, max(1, n_dev // 2), n_dev})

    model = get_model(model_name)
    data = ShardedDataIterator(
        synthetic_dataset(model.synth_batch, 4096),
        global_batch_size=max(64, 8 * n_dev),
    )
    coord = LocalCoordinator(target_world=1, max_world=n_dev)
    for i in range(n_dev):
        coord.register(f"t{i}")
    et = ElasticTrainer(
        model,
        optax.sgd(0.05),
        data,
        coord,
        devices=devices,
        # Coprime with steps_per_phase: resizes then land BETWEEN
        # interval saves, so the measured flush is the real split flush
        # (ordered d2h + overlapped hash/spill, with flush_bg phases
        # published) — a divisible interval would dedupe every resize
        # flush against the just-landed interval save and hide it.
        checkpoint_interval=7,
    )
    # Warm the compiled-step executables for every size (abstract AOT —
    # zero device allocation) so the measured window is the true warm
    # resize path, not first-compile; production gets the same warmth
    # from the autoscaler prewarm hint + persistent compile cache.
    et.precompile(sizes)
    # The warm run must cross ONE interval save: the save path's d2h
    # snapshot-copy jits compile on their first dispatch, and without a
    # pre-cycle save the first resize's flush would pay them inside the
    # measured window (they are steady-state cost, not resize cost).
    target = max(steps_per_phase, et.checkpoint_interval + 1)
    et.run(target)

    # Count TRUE XLA compiles per resize window at the backend_compile
    # seam (persistent-cache hits bypass it): the acceptance bar is
    # ZERO inside a warm resize, and a nonzero count here names the
    # exact cycle that regressed.  The count lives in the SHARED
    # telemetry registry (edl_xla_compiles_total) — bench reads the
    # same exposition surface production scrapes, instead of the
    # private list it used to keep.
    import jax._src.compiler as _compiler

    from edl_tpu import telemetry

    m_compiles = telemetry.get_registry().counter("edl_xla_compiles_total")
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    resize_windows = []
    step_times = []
    resize_events = []
    # Per-phase samples (flush / remesh / restore / first_step) so a
    # headline regression is attributable to ONE phase (the r4->r5
    # resize_max 0.33->0.80s jump was not).
    phase_samples: dict = {}
    # Cycle up then down through world sizes (e.g. 1 -> 4 -> 8 -> 4 -> 1).
    # On a single chip every entry is 1: the resize is then forced via
    # membership churn (leave+rejoin), which runs the identical barrier.
    cycle = (sizes[1:] + sizes[:-1][::-1]) or [1, 1, 1]
    prev_w = sizes[0]
    _compiler.backend_compile = _counting_bc
    try:
        for w in cycle:
            if w == prev_w:
                coord.deregister(f"t{w - 1}")
                coord.register(f"t{w - 1}")
            else:
                coord.set_target_world(w)
            prev_w = w
            compiles_before = m_compiles.value()
            first_step_marks: dict = {}

            def on_step(rec, marks=first_step_marks):
                # compile counter right after the FIRST step of each
                # generation: (mark - before) bounds the whole
                # resize-window-plus-first-step compile count, before
                # any later interval save's copy jits muddy it.
                if rec.generation not in marks:
                    marks[rec.generation] = m_compiles.value()

            et.maybe_resize()
            target += steps_per_phase
            et.run(target, on_step=on_step)
            gen = et.generation
            first = next(r for r in et.history if r.generation == gen)
            # Window = resize barrier (event.seconds) + first post-resize
            # step.
            event = et.resize_events[-1]
            assert event.generation == gen
            resize_windows.append(event.seconds + first.seconds)
            for name, secs in (event.phase_seconds or {}).items():
                phase_samples.setdefault(name, []).append(secs)
            phase_samples.setdefault("first_step", []).append(first.seconds)
            step_times.extend(r.seconds for r in et.history[-3:])
            resize_events.append(
                {
                    "world_size": event.world_size,
                    "graceful": event.graceful,
                    "seconds": round(event.seconds, 4),
                    "first_step_s": round(first.seconds, 4),
                    "xla_compiles": int(
                        first_step_marks.get(gen, m_compiles.value())
                        - compiles_before
                    ),
                    "phase_seconds": event.phase_seconds,
                }
            )
    finally:
        _compiler.backend_compile = _real_bc

    # Join any in-flight async checkpoint thread before teardown (a live
    # device->host copy racing interpreter exit aborts the TPU runtime).
    et.store.wait()

    # Steady-state telemetry overhead: time the EXACT per-step ops the
    # elastic loop performs (recorder context stamp + steps counter inc
    # + step-seconds histogram observe) on a scoped throwaway registry,
    # and express the per-step cost against this run's median step time
    # — the default-on registry's acceptance bar is < 1%.
    import time

    median_step = statistics.median(step_times)
    with telemetry.scoped() as (treg, trec):
        tc = treg.counter("edl_steps_total")
        th = treg.histogram("edl_step_seconds")
        n_ops = 20000
        t0 = time.perf_counter()
        for i in range(n_ops):
            trec.set_context(i, 0)
            tc.inc()
            th.observe(0.001)
        per_step_overhead = (time.perf_counter() - t0) / n_ops

    # Goodput ledger across the whole cycle (steady stepping + every
    # resize + any replay), read from the same shared registry a
    # production scrape sees: the fraction of wall clock spent
    # stepping, with the resizing[:phase] / holding / replaying
    # decomposition the autoscaler's decision log records.
    from edl_tpu.telemetry import goodput_decomposition

    goodput = goodput_decomposition(
        telemetry.get_registry().snapshot()
    )

    return {
        "telemetry": {
            "per_step_overhead_s": round(per_step_overhead, 9),
            "median_step_s": round(median_step, 6),
            "overhead_frac": round(per_step_overhead / median_step, 6),
            # read back from the SHARED registry (what /metrics serves)
            "steps_total": et._m_steps.value(),
        },
        "goodput": goodput,
        "goodput_frac": (goodput or {}).get("frac"),
        "resize_s": statistics.median(resize_windows),
        "resize_max_s": max(resize_windows),
        "step_s": statistics.median(step_times),
        "n_devices": n_dev,
        "world_cycle": cycle,
        "resize_phases": {
            name: {
                "median_s": round(statistics.median(xs), 4),
                "max_s": round(max(xs), 4),
            }
            for name, xs in sorted(phase_samples.items())
        },
        # Per-resize attribution (the r5 honesty fix): every resize's
        # full phase breakdown + its true-compile count, published into
        # the round record so the NEXT regression is attributable to
        # one phase of one cycle instead of a single opaque max.
        "resize_events": resize_events,
        "warm_resize_xla_compiles": max(
            (ev["xla_compiles"] for ev in resize_events), default=0
        ),
    }


V5E_BF16_PEAK_PER_CHIP = 197e12


def _timed_train_loop(model, batch_size: int, steps: int) -> dict:
    """Shared measurement harness: compile-warm, pre-staged device
    batches, float(loss) sync at the timing boundaries.

    Pre-staging matters on a tunneled platform where each
    host->device transfer blocks ~15ms and would pollute the compute
    number (production pipelines prefetch/overlap; the resize bench
    covers the data path separately).  The float(loss) sync matters
    because block_until_ready returns before device completion on the
    tunnel and wildly under-measures."""
    import time

    import jax
    import optax

    from edl_tpu.parallel.mesh import dp_mesh
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.train import Trainer

    n_dev = len(jax.devices())
    mesh = dp_mesh(n_dev)
    trainer = Trainer(model, optax.adamw(1e-4), mesh)
    state = trainer.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(model.synth_batch, max(64, 2 * batch_size)),
        global_batch_size=batch_size,
    )
    batches = [data.device_batch(s, mesh) for s in range(steps + 1)]
    jax.block_until_ready(batches)
    state, metrics = trainer.step(state, batches[0])  # compile warm-up
    float(metrics["loss"])
    t0 = time.perf_counter()
    for s in range(1, steps + 1):
        state, metrics = trainer.step(state, batches[s])
    float(metrics["loss"])  # sync: the whole chain must have executed
    dt = (time.perf_counter() - t0) / steps
    on_tpu = jax.default_backend() == "tpu"
    peak = V5E_BF16_PEAK_PER_CHIP * n_dev
    # Trained tokens/example comes from the MODEL, not a caller-passed
    # constant that could silently diverge from the actual shapes
    # (ADVICE r3); fall back to the widest batch dim for token models
    # registered without the field.
    seq_len = model.tokens_per_example or max(
        (v.shape[1] for v in batches[0].values() if v.ndim >= 2), default=1
    )
    out = {
        "step_s": dt,
        "examples_per_s": batch_size / dt,
        "tokens_per_s": batch_size * seq_len / dt,
        "mfu": model.flops_per_example * batch_size / dt / peak
        if on_tpu
        else 0.0,
        "batch": batch_size,
        "seq_len": seq_len,
    }
    # Model-specific quality counters ride along (e.g. the MoE family's
    # capacity-drop rate — an MFU figure must not hide dropped compute).
    for k, v in metrics.items():
        if k.startswith("moe_"):
            out[k] = round(float(v), 5)
    return out


def bench_transformer_throughput(steps: int = 20) -> dict:
    """Flagship transformer-base training-step throughput on the local
    device(s): tokens/s and MFU vs v5e bf16 peak (197 TFLOP/s/chip)."""
    import jax

    from edl_tpu.models.base import get_model

    n_dev = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    model = get_model("transformer_base", tiny=not on_tpu)
    batch_size = 64 * n_dev if on_tpu else 2 * n_dev
    return _timed_train_loop(model, batch_size, steps)


def bench_longcontext_lm(seq_len: int = 2048, batch: int = 8, steps: int = 8) -> dict:
    """Decoder-only LM at long context on the Pallas flash-attention
    path (XLA's fused attention OOMs here: its [B, H, T, T] f32 scores
    alone exceed HBM at training batch sizes).  Evidence for the
    long-context capability bar (SURVEY.md §5.7 — absent in the 2018
    reference; first-class in the rebuild).

    Runs in a fresh subprocess BEFORE any other section initializes the
    TPU in this process: a second process sharing the (tunneled) chip
    time-slices it and inflates this model's step ~70%.  The parent
    must not import jax before spawning."""
    return _run_bench_child(
        "--longcontext-child", str(seq_len), str(batch), str(steps)
    )


def _longcontext_child(seq_len: int, batch: int, steps: int):
    import jax

    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "flash path is TPU-only"}))
        return
    from edl_tpu.models.base import get_model

    model = get_model("transformer_lm", seq_len=seq_len)
    print(json.dumps(_timed_train_loop(model, batch, steps)))


def bench_moe_lm(batch: int = 8, steps: int = 8, group: int = 0) -> dict:
    """Full-size MoE LM (12L x 8 experts, T=2048, grouped top-1
    routing) — the expert-parallel family's single-chip figure (MFU is
    ACTIVE FLOPs: one expert per token plus routing einsums).  Child
    process for the same chip-isolation reason as long context.
    ``group`` overrides the routing group width (0 = model default)."""
    return _run_bench_child(
        "--moe-child", str(batch), str(steps), str(group)
    )


def _moe_child(batch: int, steps: int, group: int = 0):
    import jax

    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "full-size MoE bench is TPU-only"}))
        return
    from edl_tpu.models.base import get_model

    kwargs = {"group_size": group} if group else {}
    out = _timed_train_loop(get_model("moe_lm", **kwargs), batch, steps)
    print(json.dumps(out))


def _run_bench_child(*argv: str, env=None) -> dict:
    """Spawn this file as a child bench section and parse the JSON line
    it prints last (warnings go to stderr, so the parse is safe)."""
    import os
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *argv],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{argv[0]} subprocess rc={proc.returncode}: "
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_mnist_throughput(steps: int = 20) -> dict:
    """MNIST ConvNet training-step throughput — the BASELINE config 1/2
    model finally gets published numbers (VERDICT r5 #8): step_s and
    examples/s on the local device(s)."""
    import jax

    from edl_tpu.models.base import get_model

    n_dev = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    batch = (256 if on_tpu else 32) * n_dev
    r = _timed_train_loop(get_model("mnist"), batch, steps)
    # images, not tokens: report examples/s and drop the LM-shaped keys
    return {
        "step_s": round(r["step_s"], 5),
        "examples_per_s": round(r["examples_per_s"], 1),
        "batch": r["batch"],
    }


def bench_serving() -> dict:
    """Elastic inference serving — moved to ``bench_lib.serving`` (the
    ROADMAP-item-5 per-section split; the sweep now rides the shared
    OPEN-LOOP arrival generator in ``bench_lib.load``)."""
    from bench_lib.serving import bench_serving as _bench_serving

    return _bench_serving()


def bench_fleet() -> dict:
    """Multi-job fleet market under a scripted traffic storm
    (``bench_lib.fleet``): REAL launcher pods, one chip inventory, a
    serving p95 spike that preempts the lowest-priority trainer via a
    consensus-clean scale-down and gives the chips back on recovery —
    cluster-wide goodput decomposition, chips-over-time, SLO
    attainment, stop-step skew (asserted 0), and the storm's
    warm-resize true-compile count (from real worker journals)."""
    from bench_lib.fleet import bench_fleet as _bench_fleet

    return _bench_fleet()


def bench_steady_state(steps: int = 30) -> dict:
    """Steady-state step-pipeline A/B — moved to
    ``bench_lib.steady_state`` (the ROADMAP-item-5 per-module rule:
    sections move as they next change; same sections, same
    thresholds)."""
    from bench_lib.steady_state import bench_steady_state as _bench_ss

    return _bench_ss(steps=steps)


def bench_cpu_cross_size(n_devices: int = 8) -> dict:
    """True cross-size resize (1 -> n/2 -> n -> n/2 -> 1) measured on a
    forced ``n_devices`` virtual-CPU mesh in a hermetic subprocess.

    The single-chip headline above can only exercise the leave/rejoin
    barrier (world stays 1); this figure tracks the real re-mesh +
    resharding-restore path the <60s BASELINE.md budget is about.
    """
    from edl_tpu.utils.hermetic import virtual_cpu_env

    return _run_bench_child(
        "--cross-size-child", env=virtual_cpu_env(n_devices)
    )


def bench_restore_paths() -> dict:
    """Joiner restore paths side by side, plus the multi-source fabric
    sweep to >= 2GB simulated state — moved to ``bench_lib/restore.py``
    (ROADMAP item 5's per-module rule: sections move as they next
    change)."""
    from bench_lib.restore import run_restore_paths

    return run_restore_paths()


def bench_scale_down() -> dict:
    """Scale-down agreement on a REAL multi-process CPU world: four
    launcher pods form a 4-wide world through the HTTP coordinator,
    the target drops to 2, and the consensus step bus quiesces every
    member at one agreed stop step before any teardown.

    Published: retarget->quiesce latency (the time from the retarget
    landing to the slowest member parking at the boundary),
    retarget->stepping (until the survivors step at world 2), the
    agreed stop step, and the stop-step SKEW across all four members'
    last old-world steps — asserted 0: "every member leaves the old
    world at the same step boundary" is the claim this section exists
    to keep measured (the pre-consensus poll-skew race hung 2/5 runs
    of the equivalent test on a loaded box)."""
    import json as _json
    import os
    import signal
    import subprocess
    import tempfile
    import time

    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    tmp = tempfile.mkdtemp(prefix="edl-bench-scaledown-")
    coord = LocalCoordinator(
        target_world=4, max_world=4, heartbeat_timeout=60.0,
        legal_sizes=[1, 2, 4],
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    names = ("s1", "s2", "s3", "s4")
    hist = {n: os.path.join(tmp, f"{n}.jsonl") for n in names}
    events = {n: os.path.join(tmp, f"{n}.events.jsonl") for n in names}
    here = os.path.dirname(os.path.abspath(__file__))
    procs = []

    def read_jsonl(path):
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(_json.loads(line))
                    except _json.JSONDecodeError:
                        pass  # partially written tail
        return out

    def steps_at(name, world):
        return [
            r["step"]
            for r in read_jsonl(hist[name])
            if "step" in r and r.get("world_size") == world
        ]

    try:
        for i, n in enumerate(names):
            env = dict(os.environ)
            env["EDL_POD_NAME"] = n
            env["EDL_FLIGHT_RECORDER_FILE"] = events[n]
            env["XLA_FLAGS"] = " ".join(
                f
                for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith(
                    "--xla_force_host_platform_device_count"
                )
            )
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-u", "-m", "edl_tpu.launcher",
                        "--entrypoint", "fit_a_line",
                        "--steps", "200000",
                        "--coordinator", caddr,
                        "--address", f"127.0.0.1:{12400 + 100 * i}",
                        "--platform", "cpu",
                        "--global-batch-size", "8",
                        "--checkpoint-interval", "50",
                        "--history-file", hist[n],
                        "--lr", "1e-2",
                    ],
                    env=env,
                    cwd=here,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )

        def wait_for(pred, timeout, what):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return
                for p in procs:
                    if p.poll() is not None and p.returncode != 0:
                        raise RuntimeError(
                            f"scale_down worker died waiting for {what}: "
                            f"{p.stdout.read()[-2000:]}"
                        )
                time.sleep(0.25)
            raise RuntimeError(f"scale_down bench timed out on {what}")

        wait_for(
            lambda: all(len(steps_at(n, 4)) >= 5 for n in names),
            300,
            "the 4-pod world to step",
        )
        t0_wall = time.time()
        t0 = time.monotonic()
        coord.set_target_world(2)
        # The coordinator keeps the FIRST-registered members (join
        # order = rank order); with all four spawned at once that
        # order is a race — read the survivors from the plan.
        survivors = list(coord.plan().members)
        wait_for(
            lambda: all(steps_at(n, 2) for n in survivors),
            300,
            "the survivors to step at world 2",
        )
        stepping_s = time.monotonic() - t0
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait(timeout=60)

        # Every member's last old-world step: the SKEW across them is
        # the claim (0 = one agreed boundary, nobody left early).
        last_old = {n: max(steps_at(n, 4)) for n in names}
        skew = max(last_old.values()) - min(last_old.values())
        assert skew == 0, f"stop-step skew {skew}: {last_old}"
        down = [
            r["resize"]
            for r in read_jsonl(hist[survivors[0]])
            if "resize" in r and r["resize"]["world_size"] == 2
        ]
        stop_step = down[-1]["stop_step"] if down else -1
        assert stop_step == last_old[survivors[0]] + 1, (
            stop_step,
            last_old,
        )
        # Quiesce latency from the members' flight recorders: the
        # consensus.quiesce stamp of the SLOWEST member vs the
        # retarget's wall clock.
        quiesce_walls = [
            ev.get("wall", 0.0)
            for n in names
            for ev in read_jsonl(events[n])
            if ev.get("kind") == "consensus.quiesce"
        ]
        quiesce_s = (
            max(quiesce_walls) - t0_wall if quiesce_walls else None
        )
        return {
            "world_from": 4,
            "world_to": 2,
            "processes": 4,
            "stop_step": stop_step,
            "stop_skew_steps": skew,
            "retarget_to_quiesce_s": (
                round(quiesce_s, 4) if quiesce_s is not None else None
            ),
            "retarget_to_stepping_s": round(stepping_s, 4),
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def _attempt(fn, label: str, retries: int = 1):
    """Run a bench section; on failure print the traceback to stderr and
    return an ``{"error": ...}`` dict instead of silently dropping data.
    One retry absorbs transient platform flakes (e.g. a mid-flight libtpu
    upgrade on the tunneled device) without hiding persistent failures."""
    import traceback

    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:
            print(f"[bench] {label} attempt {attempt + 1} failed:", file=sys.stderr)
            traceback.print_exc()
            err = f"{type(e).__name__}: {e}"
    return {"error": err[:500]}


def _platform() -> str:
    import jax

    return jax.default_backend()


def _lm_summary(r: dict) -> dict:
    """Per-model bench summary (one shape for every LM section); error
    and skipped records pass through untouched.  Model-specific quality
    counters (the ``moe_`` keys, e.g. the capacity-drop rate) pass
    through too: an MFU figure must not hide dropped compute, and
    stripping them here was how the r5 record lost the MoE drop rate
    (VERDICT r5)."""
    if "error" in r or "skipped" in r:
        return r
    out = {
        "step_s": round(r["step_s"], 5),
        "tokens_per_s": round(r["tokens_per_s"]),
        "mfu": round(r["mfu"], 4),
        "batch": r["batch"],
        "seq_len": r["seq_len"],
    }
    out.update({k: v for k, v in r.items() if k.startswith("moe_")})
    return out


def main():
    # Long-context first: its child must own the chip alone (this
    # process has not initialized a TPU client yet).
    lc = _attempt(bench_longcontext_lm, "longcontext_lm", retries=0)
    lc4k = _attempt(
        lambda: bench_longcontext_lm(seq_len=4096, batch=4, steps=4),
        "longcontext_lm_4k",
        retries=0,
    )
    # T=8192/16384 single-chip: r4's wall was T=4096 (the merged
    # backward overflowed the DEFAULT 16MB scoped-VMEM limit, and
    # nothing fit at 8k); the raised per-shape VMEM limit
    # (flash_attention._vmem_limit) runs the merged kernel clean to 16k.
    lc8k = _attempt(
        lambda: bench_longcontext_lm(seq_len=8192, batch=2, steps=4),
        "longcontext_lm_8k",
        retries=0,
    )
    lc16k = _attempt(
        lambda: bench_longcontext_lm(seq_len=16384, batch=1, steps=4),
        "longcontext_lm_16k",
        retries=0,
    )
    moe = _attempt(bench_moe_lm, "moe_lm", retries=0)
    r = _attempt(bench_resize, "resize")
    thr = _attempt(bench_transformer_throughput, "transformer_base")
    mnist = _attempt(bench_mnist_throughput, "mnist", retries=0)
    steady = _attempt(bench_steady_state, "steady_state", retries=0)
    cross = _attempt(bench_cpu_cross_size, "cpu_cross_size", retries=0)
    restore = _attempt(bench_restore_paths, "restore_paths", retries=0)
    scale_down = _attempt(bench_scale_down, "scale_down", retries=0)
    serving = _attempt(bench_serving, "serving", retries=0)
    fleet = _attempt(bench_fleet, "fleet", retries=0)
    if "error" in r:
        # The headline section itself died: emit an explicit error record
        # rather than nothing (the driver still gets one JSON line).
        print(
            json.dumps(
                {
                    "metric": "elastic_resize_latency",
                    "value": None,
                    "unit": "s",
                    "vs_baseline": None,
                    "detail": {"error": r["error"], "transformer_base": thr,
                               "longcontext_lm": lc,
                               "longcontext_lm_4k": lc4k,
                               "longcontext_lm_8k": lc8k,
                               "longcontext_lm_16k": lc16k, "moe_lm": moe,
                               "mnist": mnist,
                               "steady_state": steady,
                               "cpu_cross_size": cross,
                               "restore_paths": restore,
                               "scale_down": scale_down,
                               "serving": serving,
                               "fleet": fleet},
                }
            )
        )
        sys.exit(1)
    value = round(r["resize_s"], 4)
    print(
        json.dumps(
            {
                "metric": "elastic_resize_latency",
                "value": value,
                "unit": "s",
                "vs_baseline": round(RESIZE_BUDGET_S / max(value, 1e-9), 2),
                "detail": {
                    "resize_max_s": round(r["resize_max_s"], 4),
                    "median_step_s": round(r["step_s"], 5),
                    # default-on registry cost per step vs the median
                    # step (the < 1% acceptance bar of ISSUE 6)
                    "telemetry": r.get("telemetry", {}),
                    "n_devices": r["n_devices"],
                    "world_cycle": r["world_cycle"],
                    "resize_phases": r.get("resize_phases", {}),
                    "resize_events": r.get("resize_events", []),
                    "warm_resize_xla_compiles": r.get(
                        "warm_resize_xla_compiles"
                    ),
                    "budget_s": RESIZE_BUDGET_S,
                    # BASELINE config 1/2 throughput (VERDICT r5 #8)
                    "mnist": mnist,
                    # pipeline on/off A/B with per-step phase breakdown
                    "steady_state": steady,
                    "transformer_base": _lm_summary(thr),
                    "longcontext_lm": _lm_summary(lc),
                    "longcontext_lm_4k": _lm_summary(lc4k),
                    "longcontext_lm_8k": _lm_summary(lc8k),
                    "longcontext_lm_16k": _lm_summary(lc16k),
                    "moe_lm": _lm_summary(moe),
                    "cpu_cross_size": (
                        cross
                        if "error" in cross
                        else {
                            "resize_s": round(cross["resize_s"], 4),
                            "resize_max_s": round(cross["resize_max_s"], 4),
                            "n_devices": cross["n_devices"],
                            "world_cycle": cross["world_cycle"],
                            "resize_phases": cross.get("resize_phases", {}),
                            "resize_events": cross.get("resize_events", []),
                            "warm_resize_xla_compiles": cross.get(
                                "warm_resize_xla_compiles"
                            ),
                        }
                    ),
                    "restore_paths": restore,
                    # retarget->quiesce latency + stop-step skew
                    # (asserted 0) across a real 4->2 process world
                    "scale_down": scale_down,
                    # elastic inference serving: offered-load sweep
                    # (p50/p95/occupancy), 0-compile request path,
                    # hot-swap with zero failed/dropped requests,
                    # pre-warmed scale-up first request
                    "serving": serving,
                    # multi-job fleet market: scripted storm on real
                    # processes — spike -> consensus-clean preemption
                    # of the lowest-priority trainer -> recovery, with
                    # per-job goodput, chips-over-time, SLO attainment
                    "fleet": fleet,
                    # platform honesty: TPU rounds and CPU-box rounds
                    # must not be compared line to line
                    "platform": _platform(),
                },
            }
        )
    )


def _cross_size_child():
    """Child entry: measure bench_resize on the forced-CPU mesh and print
    its raw dict as JSON (consumed by bench_cpu_cross_size)."""
    from edl_tpu.utils.hermetic import pin_cpu_platform

    pin_cpu_platform()
    r = bench_resize(steps_per_phase=5)
    print(json.dumps(r))


if __name__ == "__main__":
    if "--cross-size-child" in sys.argv:
        _cross_size_child()
    elif "--longcontext-child" in sys.argv:
        i = sys.argv.index("--longcontext-child")
        sl, b, st = (int(x) for x in sys.argv[i + 1 : i + 4])
        _longcontext_child(sl, b, st)
    elif "--moe-child" in sys.argv:
        i = sys.argv.index("--moe-child")
        rest = [int(x) for x in sys.argv[i + 1 :][:3]]
        _moe_child(*rest)
    else:
        main()
