#!/usr/bin/env bash
# CI entrypoint (ref analog: .travis.yml:10-18 — lint + `go test`).
# Lint gate first (tools/lint.py, the gofmt/govet/golint analog for an
# image with no Python linters installed), then the whole suite on an
# 8-device virtual-CPU mesh: tests/conftest.py forces JAX_PLATFORMS=cpu
# + --xla_force_host_platform_device_count=8, so multi-chip sharding
# paths execute without TPU hardware.
set -euo pipefail
cd "$(dirname "$0")"

# Persistent XLA compilation cache: repeated CI runs stop re-paying the
# identical CPU-mesh compiles (the same mechanism trainer pods use via
# EDL_COMPILE_CACHE_DIR).  Threshold drops to cache-everything — CPU
# test compiles are mostly under jax's 1s default and would never land.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-${TMPDIR:-/tmp}/edl-xla-cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-0}"
export JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="${JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES:--1}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

python tools/lint.py

# Structural bench-regression gates (ROADMAP item 5): assert the
# per-section invariants — scale-down stop-step skew == 0, serving
# steady-state XLA compiles == 0, warm-resize compiles == 0, fleet
# SLO attainment, latency ceilings — against the checked-in
# thresholds, over the committed BENCH snapshot (or a fresh record
# via EDL_BENCH_RECORD=path).  Milliseconds; a violated baseline
# fails before the suite spends its budget.
python tools/check_bench.py "${EDL_BENCH_RECORD:-BENCH_r15.json}" \
  --thresholds bench_thresholds.json

# Stress lane (EDL_STRESS=1): rerun the multipod elastic scale-down
# tests N times under the tier-1 timeout — the reproducer that hung
# 2/5 runs on a loaded box before the consensus step bus (data-plane
# stop-step agreement), now expected green every iteration.  The
# delayed-poll chaos test rides along: it provokes the exact poll-skew
# shape deterministically.  Since ISSUE 15 the SERVING chaos soak
# (kills + torn swap + wedged dispatch + drains + coordinator restart,
# bit-identical journals per seed) reruns in the same loop — drain/
# watchdog races are exactly the class a single green run can hide.
# Since ISSUE 16 the live-KV MIGRATION soak (kill/torn/exhausted/swap
# chaos mid-push, every fallback rung exercised, bit-identical
# journals per seed) joins it for the same reason.  Since ISSUE 20 the
# ROUTER chaos soak (refused backends, failing probes, mid-stream
# cuts, drain steers — eject/readmit/redrive all exercised,
# bit-identical journals per seed) reruns in the loop too: the front
# door's retry/eject ladder is timing-adjacent by construction.
if [ "${EDL_STRESS:-0}" = "1" ]; then
  N="${EDL_STRESS_N:-5}"
  # Post-mortem wiring: each iteration leaves a metrics snapshot +
  # flight-recorder journal; on failure the journal is merged into a
  # Chrome-trace/Perfetto timeline (edl trace) next to the snapshot,
  # so a stress flake ships with its causal picture attached.
  export EDL_METRICS_ARTIFACT="${EDL_METRICS_ARTIFACT:-${TMPDIR:-/tmp}/edl-stress-metrics.prom}"
  for i in $(seq 1 "$N"); do
    echo "[stress] multipod scale-down iteration $i/$N"
    if ! timeout -k 10 870 python -m pytest \
      tests/test_multipod.py tests/test_serving_chaos.py \
      tests/test_serving_migrate.py tests/test_router.py -x -q \
      -k "elastic_1_2_1 or delayed_poll or serving_chaos or migration_soak or router_chaos_soak" \
      -p no:cacheprovider "$@"; then
      echo "[stress] FAILED iteration $i/$N"
      events="${EDL_METRICS_ARTIFACT%.prom}.events.jsonl"
      trace_out="${EDL_METRICS_ARTIFACT%.prom}.trace.json"
      # A timeout/SIGKILL kills pytest before its sessionfinish hook
      # writes the journal — the artifacts then simply don't exist;
      # say so instead of exiting silently.
      if [ -f "$events" ]; then
        python -m edl_tpu.cli trace --journal "pytest=$events" \
          --out "$trace_out" --summary || true
        echo "metrics snapshot artifact: $EDL_METRICS_ARTIFACT"
        echo "merged trace artifact:     $trace_out"
      else
        echo "no flight-recorder journal at $events (pytest killed" \
          "before its sessionfinish hook could spill one)"
      fi
      exit 1
    fi
  done
  echo "[stress] $N/$N iterations green"
  exit 0
fi

# Metrics snapshot artifact: tests/conftest.py's sessionfinish hook
# writes the process-global telemetry registry's Prometheus exposition
# (+ the flight-recorder tail) here, so every tier-1 run leaves an
# inspectable record of what the suite's training actually did.
export EDL_METRICS_ARTIFACT="${EDL_METRICS_ARTIFACT:-${TMPDIR:-/tmp}/edl-ci-metrics.prom}"

# Tier-1: the full quick suite INCLUDING the seeded single-cycle chaos
# soak (tests/test_chaos.py).  The multi-cycle soak is marked `slow`
# and excluded so the tier-1 budget (870s) holds; run it explicitly
# with `./ci.sh -m slow` (the -m below is overridden by a later -m).
python -m pytest tests/ -x -q -m "not slow" "$@"
if [ -f "$EDL_METRICS_ARTIFACT" ]; then
  echo "metrics snapshot artifact: $EDL_METRICS_ARTIFACT"
  echo "flight recorder artifact:  ${EDL_METRICS_ARTIFACT%.prom}.events.jsonl"
fi
