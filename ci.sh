#!/usr/bin/env bash
# CI entrypoint (ref analog: .travis.yml:10-18 — lint + `go test`).
# Lint gate first (tools/lint.py, the gofmt/govet/golint analog for an
# image with no Python linters installed), then the whole suite on an
# 8-device virtual-CPU mesh: tests/conftest.py forces JAX_PLATFORMS=cpu
# + --xla_force_host_platform_device_count=8, so multi-chip sharding
# paths execute without TPU hardware.
set -euo pipefail
cd "$(dirname "$0")"

python tools/lint.py
# Tier-1: the full quick suite INCLUDING the seeded single-cycle chaos
# soak (tests/test_chaos.py).  The multi-cycle soak is marked `slow`
# and excluded so the tier-1 budget (870s) holds; run it explicitly
# with `./ci.sh -m slow` (the -m below is overridden by a later -m).
python -m pytest tests/ -x -q -m "not slow" "$@"
