"""Bench section ``serving.router`` (ISSUE 20): the fleet front door
holds client-visible p95 flat through the exact churn the serving
plane absorbs underneath it.

Open-loop ``/predict`` load (bench_lib.load's arrival discipline; the
submits ride a thread pool so a slow answer never throttles the
arrival process) flows through an in-process ``RequestRouter`` over
THREE real HTTP serving replicas.  A baseline window measures the
clean-fleet client p95; then the same load runs while the fleet takes,
in order:

- a **rolling drain** — the scale-down actuator's shape: drain intent
  to the router first (steer-before-503), then the replica's graceful
  drain, then a pre-warmed replacement joins the plan;
- a **hot swap** — a new checkpoint step lands in the shared store and
  every replica re-binds weights under load;
- one **abrupt kill** — a replica's HTTP front dies with requests in
  flight (no drain, no deregistration: the router's passive health
  must eject it off consecutive failures).

Gated: client-visible failures == 0 (every request answers through
the front door), churn-window p95 <= 2x the baseline p95, and 0
steady-state XLA compiles (routing and failover never touch the
compile path).  The seeded router chaos soak (the same helper the
EDL_STRESS lane reruns) runs twice and its recorder digests + stage
logs must be bit-identical — the determinism claim as a bench figure.
"""

from __future__ import annotations


def bench_router() -> dict:
    import threading
    import time
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bench_lib.load import arrival_offsets, run_open_loop
    from edl_tpu import telemetry
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import (
        ContinuousBatcher,
        InferenceEngine,
        RequestRouter,
        ServingReplica,
        ServingServer,
    )

    model = get_model("fit_a_line")
    params = model.init_params(jax.random.key(0))
    opt = optax.adam(1e-3)

    def state_at(step: int) -> TrainState:
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            params=params,
            opt_state=opt.init(params),
        )

    store = HostDRAMStore()
    store.save_async(state_at(1))
    store.wait()
    coord = LocalCoordinator(
        target_world=8, max_world=8, heartbeat_timeout=1e9
    )

    def _engine():
        e = InferenceEngine(
            model, store, devices=jax.devices()[:1], max_batch=8
        )
        e.load()
        e.warm()
        return e

    def _replica(engine, rid):
        batcher = ContinuousBatcher(
            engine, queue_limit=8192, default_deadline_s=60.0
        )
        server = ServingServer(batcher, host="127.0.0.1")
        return ServingReplica(
            engine,
            batcher=batcher,
            server=server,
            coordinator=coord,
            replica_id=rid,
            address=f"127.0.0.1:{server.port}",
            heartbeat_interval=60.0,
            telemetry_interval=1e9,
        ).start()

    # All four engines warm BEFORE the compile seam goes in: the
    # rolling replacement enters rotation pre-warmed (the /prewarm
    # contract), so its join must not count as a steady-state compile.
    engines = [_engine() for _ in range(4)]
    replicas = [
        _replica(engines[i], f"bench-rt-{i}") for i in range(3)
    ]
    router = RequestRouter(coord, retry_budget_s=20.0)
    router.sync()
    router.probe_all()

    maintain_stop = threading.Event()

    def _maintain():
        while not maintain_stop.is_set():
            try:
                router.sync()
                router.probe_all()
            except Exception:
                pass
            maintain_stop.wait(0.05)

    maintainer = threading.Thread(target=_maintain, daemon=True)
    maintainer.start()

    rng = np.random.RandomState(0)
    xs = rng.rand(64, 13).astype(np.float32)

    import jax._src.compiler as _compiler

    m_compiles = telemetry.get_registry().counter(
        "edl_xla_compiles_total"
    )
    compiles_before = m_compiles.value()
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    _compiler.backend_compile = _counting_bc
    pool = ThreadPoolExecutor(max_workers=64)
    failures = []

    def _phase(rate_rps: float, n: int) -> dict:
        """One open-loop window through the front door; every request
        either answers or lands in ``failures`` (the gated count)."""
        latencies = []
        lock = threading.Lock()

        def one(i: int) -> None:
            row = xs[i % len(xs)][None]
            t0 = time.perf_counter()
            try:
                out = router.predict({"inputs": {"x": row.tolist()}})
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)
                assert "pred" in out["outputs"]
            except Exception as e:  # noqa: BLE001 - the gated signal
                with lock:
                    failures.append(f"{type(e).__name__}: {e}")

        futures, lstats = run_open_loop(
            lambda i: pool.submit(one, i),
            arrival_offsets(rate_rps, n),
        )
        for f in futures:
            f.result(timeout=120)
        ordered = sorted(latencies)
        return {
            "n": n,
            "offered_rps": rate_rps,
            "scheduler_lag_max_s": lstats["scheduler_lag_max_s"],
            "answered": len(latencies),
            "p50_ms": round(
                ordered[len(ordered) // 2] * 1000.0, 3
            ) if ordered else None,
            "p95_ms": round(
                ordered[int(len(ordered) * 0.95)] * 1000.0, 3
            ) if ordered else None,
        }

    churn_log = []
    try:
        # warm the request path (first request may lazily touch
        # serialization paths; it is not part of either window)
        router.predict({"inputs": {"x": xs[:1].tolist()}})

        baseline = _phase(200.0, 300)

        # -- churn window: the same load while the fleet rolls --------
        churn_done = threading.Event()

        def _churn():
            try:
                # rolling drain of replica 0: intent -> graceful
                # drain -> replacement joins pre-warmed
                time.sleep(0.3)
                victim = replicas[0]
                router.mark_draining(
                    [victim.replica_id], trace="bench-roll"
                )
                r = victim.drain(budget_s=30.0)
                churn_log.append(
                    ("drain", bool(r["drained"]),
                     round(r["seconds"] * 1000.0, 1))
                )
                victim.stop()
                replicas.append(_replica(engines[3], "bench-rt-3"))
                churn_log.append(("replace", "bench-rt-3"))
                # hot swap: every replica re-binds the new step
                time.sleep(0.5)
                gen0 = engines[1].weights_generation
                store.save_async(state_at(100))
                store.wait()
                t_swap = time.perf_counter()
                while engines[1].weights_generation == gen0:
                    if time.perf_counter() - t_swap > 30:
                        break
                    time.sleep(0.002)
                churn_log.append(
                    ("swap", engines[1].weights_step,
                     round((time.perf_counter() - t_swap) * 1000.0, 1))
                )
                # abrupt kill: replica 1's front dies mid-flight; the
                # router's passive health must absorb + eject it
                time.sleep(0.3)
                replicas[1].server.stop()
                churn_log.append(("kill", replicas[1].replica_id))
            finally:
                churn_done.set()

        churn_thread = threading.Thread(target=_churn, daemon=True)
        churn_thread.start()
        during = _phase(200.0, 600)
        churn_thread.join(timeout=60)
        assert churn_done.is_set(), "churn script never finished"

        steady_compiles = int(m_compiles.value() - compiles_before)
        client_failures = len(failures)
        assert client_failures == 0, (
            f"{client_failures} client-visible failures through the "
            f"router: {failures[:3]}"
        )
        assert steady_compiles == 0, (
            f"{steady_compiles} XLA compiles on the routed request path"
        )
        p95_ratio = (
            round(during["p95_ms"] / baseline["p95_ms"], 3)
            if during["p95_ms"] and baseline["p95_ms"]
            else None
        )
        table = router.routing_table()
        killed = next(
            r for r in table["replicas"]
            if r["replica"] == "bench-rt-1"
        )
    finally:
        maintain_stop.set()
        _compiler.backend_compile = _real_bc
        pool.shutdown(wait=False)
        for rep in replicas:
            try:
                rep.stop()
            except Exception:
                pass

    # -- the seeded chaos soak, twice: determinism as a figure --------
    from tests.test_router import _run_router_soak

    d1, log1 = _run_router_soak(7)
    d2, log2 = _run_router_soak(7)
    soak = {
        "seed": 7,
        "digest": d1,
        "stages": [entry[0] for entry in log1],
        "bit_identical": bool(d1 == d2 and log1 == log2),
    }
    assert soak["bit_identical"], "router soak diverged across reruns"

    return {
        "model": "fit_a_line",
        "fleet": 3,
        "baseline": baseline,
        "during_churn": during,
        "p95_ratio": p95_ratio,
        "client_failures": client_failures,
        "steady_state_xla_compiles": steady_compiles,
        "churn_events": [list(e) for e in churn_log],
        "killed_replica_state": killed["health"],
        "soak": soak,
    }
