"""Open-loop load generation: a fixed arrival process, not clients.

Every serving/fleet "heavy traffic" figure in the round record must
come from the SAME arrival discipline, and that discipline must be
**open-loop**: arrivals fire at pre-scheduled instants regardless of
how fast the system answers.  Closed-loop clients (submit, wait,
submit) self-throttle exactly when the system degrades — they hide
queueing collapse and flatter p95 under overload, which is the
opposite of what an SLO bench is for.  (The pre-split serving sweep
slept ``1/rate`` AFTER each submit, so its offered rate silently sank
by the submit latency; this module schedules absolute arrival times.)

``arrival_offsets`` is pure and seeded — deterministic schedules make
sweep figures comparable across rounds.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Sequence


def arrival_offsets(
    rate_rps: float,
    n: int,
    process: str = "uniform",
    seed: int = 0,
) -> List[float]:
    """Scheduled arrival offsets (seconds from start) for ``n``
    requests at ``rate_rps``: ``"uniform"`` = deterministic fixed
    interarrival (the sweep default — lowest-variance estimate of a
    rate's latency); ``"poisson"`` = seeded exponential interarrivals
    of the same mean (burstier, for storm sections)."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if process == "uniform":
        return [i / rate_rps for i in range(n)]
    if process == "poisson":
        rng = random.Random(seed)
        t, out = 0.0, []
        for _ in range(n):
            out.append(t)
            t += rng.expovariate(rate_rps)
        return out
    raise ValueError(f"unknown arrival process {process!r}")


def run_open_loop(
    submit: Callable[[int], object],
    offsets: Sequence[float],
) -> tuple:
    """Fire ``submit(i)`` at each scheduled offset (sleeping to the
    absolute deadline, never adding per-request pacing on top of the
    submit's own latency).  Returns ``(tickets, stats)`` where stats
    records the offered vs achieved rate and the worst scheduler lag —
    a lag comparable to the interarrival gap means the generator
    itself became the bottleneck and the section should say so rather
    than publish a fake "achieved" rate."""
    tickets = []
    lag_max = 0.0
    t0 = time.perf_counter()
    for i, off in enumerate(offsets):
        now = time.perf_counter() - t0
        if now < off:
            time.sleep(off - now)
        else:
            lag_max = max(lag_max, now - off)
        tickets.append(submit(i))
    elapsed = time.perf_counter() - t0
    n = len(offsets)
    span = max(offsets[-1], 1e-9) if n else 1e-9
    stats = {
        "n": n,
        "offered_rps": round((n - 1) / span, 1) if n > 1 else None,
        "submit_elapsed_s": round(elapsed, 4),
        "scheduler_lag_max_s": round(lag_max, 4),
    }
    return tickets, stats
