"""Bench section: the fleet arbiter under a scripted traffic storm.

REAL processes, the whole market loop: a low-priority elastic trainer
(two launcher pods through an HTTP coordinator), a high-priority
protected trainer (one pod, its own coordinator), and a serving fleet
whose SLO signals are SCRIPTED (the storm: calm → p95 spike → clear).
The ``FleetArbiter`` ticks against one chip inventory sized so the
calm state is exactly full — the spike can only be absorbed by
preempting the lowest-priority trainer, and the recovery must give the
chips back.

What the record publishes (and the tier-1 test asserts):

- the preemption is a CONSENSUS-CLEAN scale-down: both members of the
  victim world leave at one agreed stop step (skew 0 across their
  journals), and the serving grant lands only after the victim-drain
  ack;
- every transition carries its own minted trace id from the fleet
  decision through vote/quiesce/resize to the first post-resize step;
- warm resizes perform ZERO true XLA compiles (the launcher's
  ``EDL_COUNT_XLA_COMPILES`` seam journals the per-window count into
  each member's ``step.first`` event);
- cluster-wide goodput decomposition per job (PR 7's ledger, read from
  each coordinator's merged telemetry), chips-over-time, and SLO
  attainment (the fraction of storm ticks whose serving requirement
  the market covered).

``run_fleet_storm`` is the shared driver: ``bench.py fleet`` publishes
its summary; ``tests/test_fleet_process.py`` asserts its invariants.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STEPS = 200_000  # workers stop by SIGTERM, never by running out


def _read_lines(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # partially written tail line
    return out


def _history(path):
    return [r for r in _read_lines(path) if "step" in r]


def _resizes(path):
    return [r["resize"] for r in _read_lines(path) if "resize" in r]


def _steps_at(path, world):
    return [
        r["step"] for r in _history(path) if r.get("world_size") == world
    ]


def _wait_for(pred, timeout, what, procs):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        for p in procs:
            if p.poll() is not None and p.returncode != 0:
                out = p.stdout.read() if p.stdout else ""
                raise RuntimeError(
                    f"fleet worker died (rc={p.returncode}) waiting for "
                    f"{what}:\n{out[-3000:]}"
                )
        time.sleep(0.25)
    dumps = []
    for p in procs:
        if p.poll() is None:
            p.kill()
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        out = p.stdout.read() if p.stdout else ""
        dumps.append(f"--- worker rc={p.returncode} ---\n{out[-2000:]}")
    raise RuntimeError(
        f"fleet storm timed out waiting for {what}\n" + "\n".join(dumps)
    )


def _spawn(procs, name, caddr, base_port, workdir, cache_dir):
    env = dict(os.environ)
    env["EDL_POD_NAME"] = name
    env["EDL_FLIGHT_RECORDER_FILE"] = os.path.join(
        workdir, f"{name}.events.jsonl"
    )
    # The compile-count seam: each resize window's TRUE-compile delta
    # journals into the member's step.first events, which is what lets
    # the zero-compile warm-resize claim hold for REAL processes.
    env["EDL_COUNT_XLA_COMPILES"] = "1"
    # Shared persistent XLA cache: a size compiled ONCE (by any pod,
    # any generation) deserializes ever after — the deployed-pod
    # contract (spec.compile_cache_dir), required for the storm's
    # warm-resize zero-compile invariant.
    env["EDL_COMPILE_CACHE_DIR"] = cache_dir
    # Tight telemetry cadence so goodput/clock reports land between
    # storm phases.
    env["EDL_TELEMETRY_INTERVAL"] = "1.0"
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    p = subprocess.Popen(
        [
            sys.executable, "-u", "-m", "edl_tpu.launcher",
            "--entrypoint", "fit_a_line",
            "--steps", str(STEPS),
            "--coordinator", caddr,
            "--address", f"127.0.0.1:{base_port}",
            "--platform", "cpu",
            "--global-batch-size", "8",
            "--checkpoint-interval", "25",
            "--history-file", os.path.join(workdir, f"{name}.jsonl"),
            "--lr", "1e-2",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    procs.append(p)
    return p


def run_fleet_storm(
    workdir: str,
    base_port: int = 13500,
    calm_ticks: int = 2,
    settle_s: float = 240.0,
) -> dict:
    """Drive the storm; returns the full record (see module doc)."""
    from edl_tpu.autoscaler.serving import ServingLane
    from edl_tpu.fleet import FleetArbiter, ServingBidder, TrainingBidder
    from edl_tpu.runtime.coord_service import (
        CoordinatorServer,
        HTTPCoordinator,
    )
    from edl_tpu.runtime.coordinator import LocalCoordinator

    os.makedirs(workdir, exist_ok=True)
    cache_dir = os.environ.get("EDL_COMPILE_CACHE_DIR") or os.path.join(
        workdir, "xla-cache"
    )
    os.makedirs(cache_dir, exist_ok=True)

    # One chip inventory: lo(2) + hi(1) + serve(1) == 4 — calm is full.
    total_chips = 4
    lo_coord = LocalCoordinator(
        target_world=2, max_world=2, heartbeat_timeout=60.0,
        legal_sizes=[1, 2],
    )
    hi_coord = LocalCoordinator(
        target_world=1, max_world=1, heartbeat_timeout=60.0,
        legal_sizes=[1],
    )
    serve_coord = LocalCoordinator(target_world=1, max_world=2)
    lo_server = CoordinatorServer(lo_coord, host="127.0.0.1", port=0).start()
    hi_server = CoordinatorServer(hi_coord, host="127.0.0.1", port=0).start()
    lo_addr = f"127.0.0.1:{lo_server.port}"
    hi_addr = f"127.0.0.1:{hi_server.port}"

    hist = {
        n: os.path.join(workdir, f"{n}.jsonl")
        for n in ("lo-a", "lo-b", "hi-a")
    }
    events = {
        n: os.path.join(workdir, f"{n}.events.jsonl")
        for n in ("lo-a", "lo-b", "hi-a")
    }
    procs = []
    timeline = []
    t_start = time.monotonic()

    def tick(arbiter, phase):
        rec = arbiter.run_once()
        timeline.append(
            {
                "t_s": round(time.monotonic() - t_start, 3),
                "phase": phase,
                "record": rec,
            }
        )
        return rec

    try:
        # -- phase A: form the calm fleet (warming every size) -----------
        _spawn(procs, "lo-a", lo_addr, base_port, workdir, cache_dir)
        _wait_for(
            lambda: len(_steps_at(hist["lo-a"], 1)) >= 3,
            settle_s, "lo-a stepping at world 1", procs,
        )
        _spawn(procs, "lo-b", lo_addr, base_port + 100, workdir, cache_dir)
        _wait_for(
            lambda: all(
                len(_steps_at(hist[n], 2)) >= 3 for n in ("lo-a", "lo-b")
            ),
            settle_s, "the lo world to step at 2", procs,
        )
        _spawn(procs, "hi-a", hi_addr, base_port + 200, workdir, cache_dir)
        _wait_for(
            lambda: len(_steps_at(hist["hi-a"], 1)) >= 3,
            settle_s, "hi-a stepping at world 1", procs,
        )

        # -- the market -------------------------------------------------
        scripted = {
            "p95_latency_s": 0.01,
            "queue_depth": 0,
            "rejected_total": None,
        }
        lane = ServingLane(
            serve_coord, min_replicas=1, max_replicas=2, hold_ticks=2
        )
        arbiter = FleetArbiter(
            total_chips,
            trainers=[
                TrainingBidder(
                    "lo", HTTPCoordinator(lo_addr), priority=0,
                    min_units=1, max_units=2, legal_units=[1, 2],
                ),
                TrainingBidder(
                    "hi", HTTPCoordinator(hi_addr), priority=10,
                    min_units=1, max_units=1,
                ),
            ],
            fleets=[
                ServingBidder(
                    "api", lane, signals=lambda: dict(scripted)
                )
            ],
            victim_drain_timeout=60.0,
        )

        # -- phase B: calm — the market is at its fixed point ------------
        calm = [tick(arbiter, "calm") for _ in range(calm_ticks)]
        calm_diffs = sum(
            abs(d["dry_run"]["diff"])
            for rec in calm
            if rec
            for d in rec["decisions"]
        )

        # -- phase C: spike — serving p95 blows the SLO ------------------
        scripted["p95_latency_s"] = 2.0
        t_spike = time.monotonic()
        spike = tick(arbiter, "spike")
        hi_gen_at_spike = hi_coord.generation()
        _wait_for(
            lambda: any(
                s > max(_steps_at(hist["lo-a"], 2) or [0])
                for s in _steps_at(hist["lo-a"], 1)
            ),
            settle_s, "the lo survivor stepping at world 1", procs,
        )
        spike_to_preempted_s = time.monotonic() - t_spike
        spike_hold = [tick(arbiter, "spike-hold") for _ in range(2)]

        # Stop-step skew: both lo members' last world-2 steps must be
        # the SAME boundary (the consensus agreement's claim).
        last_old = {
            n: max(_steps_at(hist[n], 2)) for n in ("lo-a", "lo-b")
        }
        skew = max(last_old.values()) - min(last_old.values())
        down = [
            r for r in _resizes(hist["lo-a"]) if r["world_size"] == 1
        ]
        stop_step = down[-1]["stop_step"] if down else -1
        assert skew == 0, f"stop-step skew {skew}: {last_old}"
        assert serve_coord.target_world() == 2, "serving fleet never grew"

        # -- phase D: clear — chips must come back -----------------------
        scripted["p95_latency_s"] = 0.001
        t_clear = time.monotonic()
        recover = []
        for i in range(4):  # hysteresis holds, then sheds + restores
            recover.append(tick(arbiter, "recover"))
            if serve_coord.target_world() == 1:
                break
        down_mark = len(_history(hist["lo-a"]))
        _wait_for(
            lambda: any(
                r.get("world_size") == 2
                for r in _history(hist["lo-a"])[down_mark:]
            ),
            settle_s, "lo restored to world 2", procs,
        )
        recover_to_restored_s = time.monotonic() - t_clear
        assert serve_coord.target_world() == 1, "serving never shed"

        # One more telemetry cadence so tails/goodput reach coordinators.
        time.sleep(2.5)
        goodput = {}
        for name, addr in (("lo", lo_addr), ("hi", hi_addr)):
            try:
                goodput[name] = HTTPCoordinator(addr).telemetry().get(
                    "goodput"
                )
            except Exception:
                goodput[name] = None
        # Before the SIGTERMs: a graceful leave bumps the generation.
        hi_generation_stable = hi_coord.generation() == hi_gen_at_spike

        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait(timeout=60)

        # -- reduce ------------------------------------------------------
        ticks = [t for t in timeline if t["record"]]
        preemptions = [
            p
            for t in ticks
            for p in t["record"]["preemptions"]
        ]
        storm_ticks = [
            t for t in ticks if t["phase"] in ("spike", "spike-hold", "recover")
        ]
        covered = 0
        for t in storm_ticks:
            serving = [
                d
                for d in t["record"]["decisions"]
                if d["kind"] == "serving"
            ]
            if all(
                (d["required_units"] or 0) <= d["dry_run"]["proposed"]
                for d in serving
            ):
                covered += 1
        slo_attainment = covered / max(1, len(storm_ticks))

        def entry(rec, job):
            for d in rec["decisions"]:
                if d["job"] == job:
                    return d
            return None

        traces = {
            "preempt_down": (entry(spike, "lo") or {}).get("trace_id"),
            "preempt_serve_up": (entry(spike, "api") or {}).get("trace_id"),
        }
        for rec in recover:
            if rec and entry(rec, "lo") and entry(rec, "lo")["dry_run"]["diff"] > 0:
                traces["restore_up"] = entry(rec, "lo")["trace_id"]
                traces["restore_serve_down"] = (
                    entry(rec, "api") or {}
                ).get("trace_id")

        member_events = {n: _read_lines(events[n]) for n in events}
        hi_resizes = _resizes(hist["hi-a"])
        record = {
            "chips_total": total_chips,
            "processes": 3,
            "calm_tick_diffs": calm_diffs,
            "preemptions": preemptions,
            "victim": preemptions[0]["victim"] if preemptions else None,
            "stop_step": stop_step,
            "stop_skew_steps": skew,
            "spike_to_preempted_s": round(spike_to_preempted_s, 3),
            "recover_to_restored_s": round(recover_to_restored_s, 3),
            "slo_attainment": round(slo_attainment, 4),
            "goodput": goodput,
            "chips_over_time": [
                {
                    "t_s": t["t_s"],
                    "phase": t["phase"],
                    "free": t["record"]["free_chips"],
                    "holdings": t["record"]["inventory"]["holdings"],
                }
                for t in ticks
            ],
            "traces": traces,
            "ticks": ticks,
            "member_events": member_events,
            "histories": {n: _history(hist[n]) for n in hist},
            "hi_resize_worlds": sorted(
                {r["world_size"] for r in hi_resizes}
            ),
            "hi_generation_stable": hi_generation_stable,
            "spike_record": spike,
            "spike_hold": spike_hold,
        }
        assert record["victim"] == "lo", record["victim"]
        return record
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        lo_server.stop()
        hi_server.stop()


def bench_fleet(workdir: str = "") -> dict:
    """The publishable summary (the full record's journals stay out of
    the round JSON)."""
    import tempfile

    workdir = workdir or tempfile.mkdtemp(prefix="edl-bench-fleet-")
    r = run_fleet_storm(workdir, base_port=13900)
    resize_compiles = _storm_resize_compiles(r)
    return {
        "chips_total": r["chips_total"],
        "processes": r["processes"],
        "victim": r["victim"],
        "preemption_steps": len(r["preemptions"]),
        "stop_step": r["stop_step"],
        "stop_skew_steps": r["stop_skew_steps"],
        "spike_to_preempted_s": r["spike_to_preempted_s"],
        "recover_to_restored_s": r["recover_to_restored_s"],
        "slo_attainment": r["slo_attainment"],
        "storm_resize_xla_compiles": resize_compiles,
        "goodput": r["goodput"],
        "chips_over_time": r["chips_over_time"],
        "hi_generation_stable": r["hi_generation_stable"],
    }


def _storm_resize_compiles(record: dict) -> int:
    """Worst per-window TRUE-compile count across the storm's traced
    transitions (preempt + restore), read from the members' step.first
    journals: the warm-resize zero-compile bar, measured on real
    processes.  Raises when ANY traced transition produced no counted
    step.first — a journal that stopped carrying the evidence must
    fail the section, not publish a vacuous 0 the ci gate waves
    through (the 'gate that silently stops measuring' class)."""
    worst = 0
    for key in ("preempt_down", "restore_up"):
        trace = record["traces"].get(key)
        if not trace:
            raise RuntimeError(f"storm transition {key} has no trace id")
        matched = 0
        for evs in record["member_events"].values():
            for ev in evs:
                if (
                    ev.get("kind") == "step.first"
                    and ev.get("trace") == trace
                    and "xla_compiles" in (ev.get("data") or {})
                ):
                    matched += 1
                    worst = max(worst, int(ev["data"]["xla_compiles"]))
        if matched == 0:
            raise RuntimeError(
                f"no counted step.first journaled for {key} "
                f"(trace {trace}): compile evidence missing"
            )
    return worst
