"""bench scale_down — consensus-clean 4->2 shrink on real processes.

ROADMAP item 5's per-module split, final tranche: the scale-down
agreement section moves here from the monolithic ``bench.py``.
``bench.py`` stays the driver that composes sections into the ONE
JSON round record.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def bench_scale_down() -> dict:
    """Scale-down agreement on a REAL multi-process CPU world: four
    launcher pods form a 4-wide world through the HTTP coordinator,
    the target drops to 2, and the consensus step bus quiesces every
    member at one agreed stop step before any teardown.

    Published: retarget->quiesce latency (the time from the retarget
    landing to the slowest member parking at the boundary),
    retarget->stepping (until the survivors step at world 2), the
    agreed stop step, and the stop-step SKEW across all four members'
    last old-world steps — asserted 0: "every member leaves the old
    world at the same step boundary" is the claim this section exists
    to keep measured (the pre-consensus poll-skew race hung 2/5 runs
    of the equivalent test on a loaded box)."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    tmp = tempfile.mkdtemp(prefix="edl-bench-scaledown-")
    coord = LocalCoordinator(
        target_world=4, max_world=4, heartbeat_timeout=60.0,
        legal_sizes=[1, 2, 4],
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    names = ("s1", "s2", "s3", "s4")
    hist = {n: os.path.join(tmp, f"{n}.jsonl") for n in names}
    events = {n: os.path.join(tmp, f"{n}.events.jsonl") for n in names}
    procs = []

    def read_jsonl(path):
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass  # partially written tail
        return out

    def steps_at(name, world):
        return [
            r["step"]
            for r in read_jsonl(hist[name])
            if "step" in r and r.get("world_size") == world
        ]

    try:
        for i, n in enumerate(names):
            env = dict(os.environ)
            env["EDL_POD_NAME"] = n
            env["EDL_FLIGHT_RECORDER_FILE"] = events[n]
            env["XLA_FLAGS"] = " ".join(
                f
                for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith(
                    "--xla_force_host_platform_device_count"
                )
            )
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-u", "-m", "edl_tpu.launcher",
                        "--entrypoint", "fit_a_line",
                        "--steps", "200000",
                        "--coordinator", caddr,
                        "--address", f"127.0.0.1:{12400 + 100 * i}",
                        "--platform", "cpu",
                        "--global-batch-size", "8",
                        "--checkpoint-interval", "50",
                        "--history-file", hist[n],
                        "--lr", "1e-2",
                    ],
                    env=env,
                    cwd=REPO,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )

        def wait_for(pred, timeout, what):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return
                for p in procs:
                    if p.poll() is not None and p.returncode != 0:
                        raise RuntimeError(
                            f"scale_down worker died waiting for {what}: "
                            f"{p.stdout.read()[-2000:]}"
                        )
                time.sleep(0.25)
            raise RuntimeError(f"scale_down bench timed out on {what}")

        wait_for(
            lambda: all(len(steps_at(n, 4)) >= 5 for n in names),
            300,
            "the 4-pod world to step",
        )
        t0_wall = time.time()
        t0 = time.monotonic()
        coord.set_target_world(2)
        # The coordinator keeps the FIRST-registered members (join
        # order = rank order); with all four spawned at once that
        # order is a race — read the survivors from the plan.
        survivors = list(coord.plan().members)
        wait_for(
            lambda: all(steps_at(n, 2) for n in survivors),
            300,
            "the survivors to step at world 2",
        )
        stepping_s = time.monotonic() - t0
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait(timeout=60)

        # Every member's last old-world step: the SKEW across them is
        # the claim (0 = one agreed boundary, nobody left early).
        last_old = {n: max(steps_at(n, 4)) for n in names}
        skew = max(last_old.values()) - min(last_old.values())
        assert skew == 0, f"stop-step skew {skew}: {last_old}"
        down = [
            r["resize"]
            for r in read_jsonl(hist[survivors[0]])
            if "resize" in r and r["resize"]["world_size"] == 2
        ]
        stop_step = down[-1]["stop_step"] if down else -1
        assert stop_step == last_old[survivors[0]] + 1, (
            stop_step,
            last_old,
        )
        # Quiesce latency from the members' flight recorders: the
        # consensus.quiesce stamp of the SLOWEST member vs the
        # retarget's wall clock.
        quiesce_walls = [
            ev.get("wall", 0.0)
            for n in names
            for ev in read_jsonl(events[n])
            if ev.get("kind") == "consensus.quiesce"
        ]
        quiesce_s = (
            max(quiesce_walls) - t0_wall if quiesce_walls else None
        )
        return {
            "world_from": 4,
            "world_to": 2,
            "processes": 4,
            "stop_step": stop_step,
            "stop_skew_steps": skew,
            "retarget_to_quiesce_s": (
                round(quiesce_s, 4) if quiesce_s is not None else None
            ),
            "retarget_to_stepping_s": round(stepping_s, 4),
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
