"""bench steady_state — step-pipeline A/B, per model.

Moved out of ``bench.py`` per ROADMAP item 5's per-module rule
(sections move as they next change).  Same sections, same thresholds:
the SAME elastic run with the async pipeline off (depth 0: per-step
host<->device sync — the pre-pipeline loop) vs on (depth 2: background
batch staging + lag-deferred metrics harvest).  Publishes median step
seconds for both modes, the speedup, the pipelined run's per-step
phase breakdown (host stage / jit dispatch / harvest device-wait), and
asserts the loss stream is bit-identical — the pipeline changes WHEN
values are read, never WHAT is computed.
"""

from __future__ import annotations

import statistics
import sys


def _attempt(fn, label: str, retries: int = 0):
    """Per-section guard (bench.py's shape): print the traceback to
    stderr and return an ``{"error": ...}`` record instead of dropping
    the whole round."""
    import traceback

    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:
            print(
                f"[bench] {label} attempt {attempt + 1} failed:",
                file=sys.stderr,
            )
            traceback.print_exc()
            err = f"{type(e).__name__}: {e}"
    return {"error": err[:500]}


def bench_steady_state(steps: int = 30) -> dict:
    import jax
    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer

    devices = jax.devices()
    n_dev = len(devices)
    on_tpu = jax.default_backend() == "tpu"
    # mnist (BASELINE config 1/2) + two LM shapes: the models whose
    # steady state the acceptance bar measures.  Tiny variants off-TPU
    # keep the CPU A/B honest about overlap without hour-long runs.
    sections = [
        ("mnist", {}, 32 * n_dev),
        ("transformer_base", {"tiny": not on_tpu}, (64 if on_tpu else 2) * n_dev),
        ("moe_lm", {"tiny": not on_tpu}, (8 if on_tpu else 2) * n_dev),
    ]
    out = {}
    for name, kwargs, batch in sections:
        def one_mode(depth, name=name, kwargs=kwargs, batch=batch):
            model = get_model(name, **kwargs)
            data = ShardedDataIterator(
                synthetic_dataset(model.synth_batch, max(2 * batch, 64)),
                global_batch_size=batch,
            )
            coord = LocalCoordinator(target_world=n_dev, max_world=n_dev)
            for i in range(n_dev):
                coord.register(f"t{i}")
            et = ElasticTrainer(
                model,
                optax.sgd(0.01),
                data,
                coord,
                devices=devices,
                checkpoint_interval=0,  # pure steady state, no saves
            )
            et.pipeline_depth = depth
            et.run(steps)
            et.store.wait()
            losses = [r.loss for r in et.history]
            warm = [r.seconds for r in et.history[3:]]  # skip compile
            stats = dict(et.pipeline_stats)
            stats.update(
                (et._stager.stats if et._stager is not None else {})
            )
            return losses, statistics.median(warm), stats

        def run_section():
            sync_losses, sync_med, _ = one_mode(0)
            pipe_losses, pipe_med, stats = one_mode(2)
            # pipeline_stats accumulate over ALL iterations (warmup
            # included), so normalize by the full step count — dividing
            # by the median's warm subset would overstate every phase.
            per_step = max(1, steps)
            # THE determinism claim, ENFORCED: a regression must fail
            # the section (surfacing in _attempt's error field), not
            # publish losses_bit_identical=false and exit 0.
            assert sync_losses == pipe_losses, (
                "steady-state loss stream diverged between pipeline "
                "off and on"
            )
            return {
                "sync_median_step_s": round(sync_med, 6),
                "pipelined_median_step_s": round(pipe_med, 6),
                "speedup": round(sync_med / max(pipe_med, 1e-9), 3),
                # THE determinism claim: identical float stream, not
                # merely allclose — the pipeline must not change math.
                "losses_bit_identical": sync_losses == pipe_losses,
                "phases": {
                    "stage_s": round(stats["stage_s"] / per_step, 6),
                    "dispatch_s": round(stats["dispatch_s"] / per_step, 6),
                    "device_wait_s": round(
                        stats["device_wait_s"] / per_step, 6
                    ),
                },
                "max_in_flight": stats["max_in_flight"],
                "staged_hits": stats.get("hits", 0),
                "staged_misses": stats.get("misses", 0),
                "batch": batch,
                "steps": steps,
            }

        out[name] = _attempt(run_section, f"steady_state:{name}", retries=0)
    return out
