"""Bench section: elastic inference serving (moved out of bench.py —
the first cut of ROADMAP item 5's per-section split).

Measures the ``edl_tpu.serving`` stack end to end: an offered-load
sweep driven by the shared OPEN-LOOP generator (``bench_lib.load`` —
fixed arrival schedule, so overload shows up as latency instead of a
silently sagging offered rate), the ZERO-compile steady-state request
path (asserted at the backend_compile seam, same as warm resizes), a
checkpoint hot-swap with zero failed/dropped requests (+ the swap
pause), and a scale-up replica answering its FIRST request on a
pre-warmed executable.

The DECODE sweep (ISSUE 13) measures the KV-cached autoregressive
path the same way: generate requests at 3 offered loads through the
token-iteration batcher — tokens/s, time-to-first-token p50/p95,
inter-token p95 — with steady-state decode asserted at ZERO XLA
compiles (prefill + decode executables are AOT-held per bucket), and
a hot swap under decode load completing with zero failed/dropped
sequences.

The INTERFERENCE sweep (ISSUE 14) measures the prefill/decode
interference chunked prefill exists to bound: a steady short-prompt
decode load takes periodic LONG-prompt admissions (the 2k-4k-token
shape at full size; scaled to the tiny context on a CPU box) under
both monolithic and chunked admission — inter-token p95 during
admissions vs the no-admission baseline (the stall ratio the
thresholds gate at <= 2x for chunked), long-prompt TTFT, the new
per-iteration stall histogram's p95, a mid-sweep hot swap, ZERO
steady-state compiles and ZERO dropped sequences.
"""

from __future__ import annotations

from bench_lib.load import arrival_offsets, run_open_loop


def _hist_delta(after, before):
    """Per-phase view of a cumulative histogram series: after - before
    (the quantiles of just the window between two snapshots)."""
    if after is None:
        return None
    if before is None:
        return after
    return {
        "buckets": list(after["buckets"]),
        "counts": [
            a - b for a, b in zip(after["counts"], before["counts"])
        ],
        "sum": after["sum"] - before["sum"],
        "count": after["count"] - before["count"],
    }


def bench_serving() -> dict:
    import threading
    import time

    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from edl_tpu import telemetry
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import ContinuousBatcher, InferenceEngine
    from edl_tpu.telemetry.aggregate import histogram_quantile

    model = get_model("mnist")
    params = model.init_params(jax.random.key(0))
    opt = optax.adam(1e-3)

    def state_at(step: int) -> TrainState:
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            params=params,
            opt_state=opt.init(params),
        )

    store = HostDRAMStore()
    store.save_async(state_at(0))
    store.wait()
    engine = InferenceEngine(
        model, store, devices=jax.devices()[:1], max_batch=32
    )
    engine.load()
    engine.warm()
    reg = telemetry.get_registry()
    m_requests = reg.counter("edl_serve_requests_total")
    h_latency = reg.histogram("edl_serve_latency_seconds")
    h_occupancy = reg.histogram("edl_serve_batch_occupancy")
    batcher = ContinuousBatcher(
        engine, queue_limit=8192, default_deadline_s=60.0
    ).start()

    rng = np.random.RandomState(0)
    pool = model.synth_batch(rng, 64)["image"]

    # Everything below the seam must be compile-free: the sweep, the
    # hot swap, and the pre-warmed scale-up replica's first request.
    import jax._src.compiler as _compiler

    m_compiles = telemetry.get_registry().counter("edl_xla_compiles_total")
    compiles_before = m_compiles.value()
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    _compiler.backend_compile = _counting_bc
    try:
        # -- offered-load sweep (open-loop arrivals) ---------------------
        sweep = []
        for offered_rps in (50, 200, 800):
            lat0 = h_latency.series()
            occ0 = h_occupancy.series()
            n_req = max(32, min(256, offered_rps))

            def submit(i):
                row = pool[i % len(pool)][None]
                return batcher.submit({"image": row})

            t0 = time.perf_counter()
            tickets, lstats = run_open_loop(
                submit, arrival_offsets(offered_rps, n_req)
            )
            for t in tickets:
                t.result(timeout=120)
            elapsed = time.perf_counter() - t0
            lat = _hist_delta(h_latency.series(), lat0)
            occ = _hist_delta(h_occupancy.series(), occ0)
            p50 = histogram_quantile(lat, 0.5)
            p95 = histogram_quantile(lat, 0.95)
            sweep.append(
                {
                    "offered_rps": offered_rps,
                    "achieved_rps": round(n_req / elapsed, 1),
                    "examples_per_s": round(n_req / elapsed, 1),
                    "scheduler_lag_max_s": lstats["scheduler_lag_max_s"],
                    "p50_ms": round(p50 * 1000, 3) if p50 else None,
                    "p95_ms": round(p95 * 1000, 3) if p95 else None,
                    "occupancy_mean": (
                        round(occ["sum"] / occ["count"], 4)
                        if occ and occ["count"]
                        else None
                    ),
                }
            )

        # -- hot swap under load -----------------------------------------
        ok0 = m_requests.value(status="ok")
        err0 = m_requests.value(status="error") + m_requests.value(
            status="expired"
        ) + m_requests.value(status="rejected")
        gen0 = engine.weights_generation
        lat0 = h_latency.series()
        stop = threading.Event()
        swap_tickets = []

        def stream():
            i = 0
            while not stop.is_set():
                swap_tickets.append(
                    batcher.submit({"image": pool[i % len(pool)][None]})
                )
                i += 1
                time.sleep(0.002)

        th = threading.Thread(target=stream, daemon=True)
        th.start()
        time.sleep(0.1)
        store.save_async(state_at(100))
        store.wait()
        t_swap = time.perf_counter()
        while engine.weights_generation == gen0:
            if time.perf_counter() - t_swap > 30:
                break
            time.sleep(0.002)
        swap_latency_s = time.perf_counter() - t_swap
        time.sleep(0.1)
        stop.set()
        th.join(timeout=10)
        for t in swap_tickets:
            t.result(timeout=120)
        failed = (
            m_requests.value(status="error")
            + m_requests.value(status="expired")
            + m_requests.value(status="rejected")
            - err0
        )
        swap_lat = _hist_delta(h_latency.series(), lat0)
        swap_p95 = histogram_quantile(swap_lat, 0.95)
        hot_swap = {
            "swapped": engine.weights_generation > gen0,
            "to_step": engine.weights_step,
            # submission->install observed from the request stream's
            # side: the serving gap a swap can add at worst
            "swap_latency_ms": round(swap_latency_s * 1000, 3),
            "requests_during_swap": len(swap_tickets),
            "completed": int(m_requests.value(status="ok") - ok0),
            "failed_or_dropped": int(failed),
            "p95_ms_during_swap": (
                round(swap_p95 * 1000, 3) if swap_p95 else None
            ),
        }
        assert hot_swap["swapped"], "hot swap never installed"
        assert failed == 0, f"{failed} requests failed/dropped in the swap"

        # Steady state = the sweep + the hot swap: both must have
        # performed ZERO true compiles (the warmed executables carried
        # every bucket, and the swap re-binds params, not programs).
        steady_compiles = int(m_compiles.value() - compiles_before)
        assert steady_compiles == 0, (
            f"{steady_compiles} XLA compiles on the steady request path"
        )

        # -- scale-up replica: first request on a pre-warmed executable --
        engine2 = InferenceEngine(
            model, store, devices=jax.devices()[:1], max_batch=32
        )
        engine2.load()
        warm_t0 = time.perf_counter()
        engine2.warm()  # before taking traffic (the /prewarm contract);
        warm_s = time.perf_counter() - warm_t0
        compiles_mark = m_compiles.value()
        t0 = time.perf_counter()
        out, meta = engine2.predict(
            engine2.coerce_inputs({"image": pool[:1]})[0]
        )
        first_request_s = time.perf_counter() - t0
        scale_up = {
            "warm_buckets": list(engine2.warm_buckets),
            "warm_s": round(warm_s, 4),
            "first_request_ms": round(first_request_s * 1000, 3),
            "first_request_xla_compiles": int(
                m_compiles.value() - compiles_mark
            ),
            "weights_step": meta["weights_step"],
        }
        assert scale_up["first_request_xla_compiles"] == 0
    finally:
        batcher.stop()
        _compiler.backend_compile = _real_bc

    return {
        "model": "mnist",
        "buckets": list(engine.buckets),
        "sweep": sweep,
        "p95_latency_ms": sweep[-1]["p95_ms"],
        "steady_state_xla_compiles": steady_compiles,
        "hot_swap": hot_swap,
        "scale_up": scale_up,
        "decode": bench_decode(),
        "interference": bench_interference(),
        "drain": bench_drain(),
        "migrate": bench_migrate(),
        "prefix": bench_prefix(),
        "tp": bench_tp(),
    }


def bench_drain() -> dict:
    """Graceful-drain section (ISSUE 15): repeated drain rounds of a
    replica under live open-loop load, with a survivor replica taking
    the redirected traffic.  Per round: admission closes (later
    submissions raise the typed DrainingError and are resubmitted to
    the survivor — the client 503-retry contract), every in-flight
    request completes, and the drain latency (admission close ->
    drained + deregister-ready) is measured.  Gated: dropped == 0 and
    drain latency p95 under the threshold — "scale-down never deletes
    an undrained replica" as a structural bench invariant."""
    import threading
    import time

    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from edl_tpu import telemetry
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import (
        ContinuousBatcher,
        DrainingError,
        InferenceEngine,
        ServingReplica,
    )

    model = get_model("mnist")
    params = model.init_params(jax.random.key(0))
    opt = optax.adam(1e-3)
    store = HostDRAMStore()
    store.save_async(
        TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt.init(params),
        )
    )
    store.wait()

    def _engine():
        e = InferenceEngine(
            model, store, devices=jax.devices()[:1], max_batch=32
        )
        e.load()
        e.warm()
        return e

    victim_engine = _engine()
    survivor = ContinuousBatcher(
        _engine(), queue_limit=8192, default_deadline_s=60.0
    ).start()
    reg = telemetry.get_registry()
    m_requests = reg.counter("edl_serve_requests_total")

    def _failures():
        return (
            m_requests.value(status="error")
            + m_requests.value(status="expired")
            + m_requests.value(status="rejected")
        )

    rng = np.random.RandomState(0)
    pool = model.synth_batch(rng, 64)["image"]
    rounds = 5
    err0 = _failures()
    latencies_ms = []
    redirected_total = 0
    drained_all = True
    completed_in_flight = 0
    try:
        for n in range(rounds):
            batcher = ContinuousBatcher(
                victim_engine, queue_limit=8192, default_deadline_s=60.0
            )
            replica = ServingReplica(
                victim_engine,
                batcher=batcher,
                replica_id=f"bench-drain-{n}",
                heartbeat_interval=60.0,
            )
            replica.start()
            stop = threading.Event()
            tickets = []
            redirected = [0]

            def driver():
                i = 0
                while not stop.is_set():
                    row = pool[i % len(pool)][None]
                    try:
                        tickets.append(batcher.submit({"image": row}))
                    except DrainingError:
                        # the 503-retry contract: route to a survivor
                        redirected[0] += 1
                        tickets.append(
                            survivor.submit({"image": row})
                        )
                    i += 1
                    time.sleep(0.001)

            th = threading.Thread(target=driver, daemon=True)
            th.start()
            time.sleep(0.05)  # load genuinely in flight
            in_flight = batcher.in_flight
            r = replica.drain(budget_s=30.0)
            stop.set()
            th.join(timeout=10)
            for t in tickets:
                t.result(timeout=120)  # every request completes SOMEWHERE
            drained_all = drained_all and bool(r["drained"])
            latencies_ms.append(round(r["seconds"] * 1000.0, 3))
            redirected_total += redirected[0]
            completed_in_flight += in_flight
            replica.stop()
    finally:
        survivor.stop()
    dropped = int(_failures() - err0)
    assert drained_all, "a bench drain missed its budget"
    assert dropped == 0, f"{dropped} requests dropped across drains"
    ordered = sorted(latencies_ms)
    return {
        "rounds": rounds,
        "drain_latency_ms": latencies_ms,
        "drain_latency_p50_ms": ordered[len(ordered) // 2],
        "drain_latency_p95_ms": ordered[-1],
        "in_flight_completed": completed_in_flight,
        "redirected_during_drain": redirected_total,
        "dropped": dropped,
        "drained_all": drained_all,
    }


def bench_decode() -> dict:
    """KV-cached autoregressive decode through the token-iteration
    batcher: generate requests at 3 offered loads (tokens/s, TTFT
    p50/p95, inter-token p95), 0 steady-state compiles asserted, and a
    hot swap under decode load with zero failed/dropped sequences."""
    import threading
    import time

    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from edl_tpu import telemetry
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import DecodeEngine, TokenContinuousBatcher
    from edl_tpu.telemetry.aggregate import histogram_quantile

    on_tpu = jax.default_backend() == "tpu"
    model = get_model("transformer_lm", tiny=not on_tpu)
    params = model.init_params(jax.random.key(0))
    opt = optax.adam(1e-3)

    def state_at(step: int, seed: int = 0) -> TrainState:
        p = (
            params
            if seed == 0
            else model.init_params(jax.random.key(seed))
        )
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            params=p,
            opt_state=opt.init(p),
        )

    store = HostDRAMStore()
    store.save_async(state_at(1))
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=8,
        block_tokens=16,
    )
    engine.load()
    engine.warm()

    reg = telemetry.get_registry()
    m_requests = reg.counter("edl_serve_requests_total")
    m_tokens = reg.counter("edl_serve_tokens_total")
    h_ttft = reg.histogram("edl_serve_ttft_seconds")
    h_intertoken = reg.histogram("edl_serve_intertoken_seconds")
    batcher = TokenContinuousBatcher(
        engine, queue_limit=8192, default_deadline_s=120.0
    ).start()

    rng = np.random.RandomState(0)
    corpus = model.synth_batch(rng, 64)["tokens"]
    max_new = 8

    import jax._src.compiler as _compiler

    m_compiles = reg.counter("edl_xla_compiles_total")
    compiles_before = m_compiles.value()
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    _compiler.backend_compile = _counting_bc
    try:
        # -- offered-load decode sweep (open-loop arrivals) --------------
        sweep = []
        for offered_rps in (8, 24, 48):
            ttft0 = h_ttft.series()
            it0 = h_intertoken.series()
            tokens0 = m_tokens.value()
            n_req = max(16, min(64, offered_rps * 2))

            def submit(i):
                plen = 5 + (i * 7) % 40
                prompt = corpus[i % len(corpus)][:plen]
                return batcher.submit_generate(
                    {"tokens": prompt}, max_new_tokens=max_new
                )

            t0 = time.perf_counter()
            tickets, lstats = run_open_loop(
                submit, arrival_offsets(offered_rps, n_req)
            )
            for t in tickets:
                t.result(timeout=240)
            elapsed = time.perf_counter() - t0
            ttft = _hist_delta(h_ttft.series(), ttft0)
            inter = _hist_delta(h_intertoken.series(), it0)
            emitted = m_tokens.value() - tokens0
            tp50 = histogram_quantile(ttft, 0.5)
            tp95 = histogram_quantile(ttft, 0.95)
            ip95 = histogram_quantile(inter, 0.95)
            sweep.append(
                {
                    "offered_rps": offered_rps,
                    "achieved_rps": round(n_req / elapsed, 1),
                    "tokens_per_s": round(emitted / elapsed, 1),
                    "scheduler_lag_max_s": lstats["scheduler_lag_max_s"],
                    "ttft_p50_ms": (
                        round(tp50 * 1000, 3) if tp50 else None
                    ),
                    "ttft_p95_ms": (
                        round(tp95 * 1000, 3) if tp95 else None
                    ),
                    "intertoken_p95_ms": (
                        round(ip95 * 1000, 3) if ip95 else None
                    ),
                }
            )

        # -- hot swap under decode load ----------------------------------
        err0 = (
            m_requests.value(status="error")
            + m_requests.value(status="expired")
            + m_requests.value(status="rejected")
        )
        gen0 = engine.weights_generation
        stop = threading.Event()
        swap_tickets = []

        def stream():
            i = 0
            while not stop.is_set():
                plen = 5 + (i * 11) % 40
                swap_tickets.append(
                    batcher.submit_generate(
                        {"tokens": corpus[i % len(corpus)][:plen]},
                        max_new_tokens=16,
                    )
                )
                i += 1
                time.sleep(0.004)

        th = threading.Thread(target=stream, daemon=True)
        th.start()
        time.sleep(0.1)
        store.save_async(state_at(100, seed=7))
        store.wait()
        t_swap = time.perf_counter()
        while engine.weights_generation == gen0:
            if time.perf_counter() - t_swap > 30:
                break
            time.sleep(0.002)
        swap_latency_s = time.perf_counter() - t_swap
        time.sleep(0.1)
        stop.set()
        th.join(timeout=10)
        results = [t.result(timeout=240) for t in swap_tickets]
        failed = (
            m_requests.value(status="error")
            + m_requests.value(status="expired")
            + m_requests.value(status="rejected")
            - err0
        )
        restarted = sum(1 for _, meta in results if meta["restarts"])
        hot_swap = {
            "swapped": engine.weights_generation > gen0,
            "to_step": engine.weights_step,
            "swap_latency_ms": round(swap_latency_s * 1000, 3),
            "sequences_during_swap": len(swap_tickets),
            "completed": len(results),
            "restarted_mid_generation": restarted,
            "failed_or_dropped": int(failed),
        }
        assert hot_swap["swapped"], "decode hot swap never installed"
        assert failed == 0, f"{failed} sequences failed/dropped in swap"

        steady_compiles = int(m_compiles.value() - compiles_before)
        assert steady_compiles == 0, (
            f"{steady_compiles} XLA compiles on the steady decode path"
        )
    finally:
        batcher.stop()
        _compiler.backend_compile = _real_bc

    return {
        "model": model.name,
        "max_seqs": engine.max_seqs,
        "block_tokens": engine.block_tokens,
        "prompt_buckets": list(engine.prompt_buckets),
        "decode_buckets": list(engine.decode_buckets),
        "max_new_tokens": max_new,
        "sweep": sweep,
        "tokens_per_s": sweep[-1]["tokens_per_s"],
        "ttft_p95_ms": sweep[-1]["ttft_p95_ms"],
        "intertoken_p95_ms": sweep[-1]["intertoken_p95_ms"],
        "steady_state_xla_compiles": steady_compiles,
        "hot_swap": hot_swap,
    }


def bench_interference() -> dict:
    """Long-prompt interference sweep (ISSUE 14): a steady short-prompt
    decode load takes periodic long-prompt admissions under monolithic
    AND chunked prefill.  Publishes, per mode: inter-token p95 with no
    admissions (baseline) and during admissions, their ratio (the
    stall the running batch experienced), long-prompt TTFT p50/p95 and
    the per-iteration prefill-stall p95.  Chunked mode also lands a
    mid-sweep hot swap.  Asserted: 0 XLA compiles across the whole
    sweep, 0 dropped sequences."""
    import threading
    import time

    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from edl_tpu import telemetry
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import DecodeEngine, TokenContinuousBatcher
    from edl_tpu.telemetry.aggregate import histogram_quantile

    on_tpu = jax.default_backend() == "tpu"
    # The long-context family IS the workload chunking exists for: 4k
    # contexts with 2k-4k-token admissions at full size; the tiny
    # 128-token context scales the same shape onto a CPU box (long
    # prompts at 3/4 .. all-but-one of the window).
    model = get_model("longcontext_lm", tiny=not on_tpu)
    params = model.init_params(jax.random.key(0))
    opt = optax.adam(1e-3)

    def state_at(step: int, seed: int = 0) -> TrainState:
        p = (
            params
            if seed == 0
            else model.init_params(jax.random.key(seed))
        )
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            params=p,
            opt_state=opt.init(p),
        )

    store = HostDRAMStore()
    store.save_async(state_at(1))
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=8,
        block_tokens=16,
        max_chunk_tokens=32,
    )
    engine.load()
    engine.warm()
    ctx = engine.max_context
    long_lens = (ctx * 3 // 4, engine.max_prompt)

    reg = telemetry.get_registry()
    m_requests = reg.counter("edl_serve_requests_total")
    h_intertoken = reg.histogram("edl_serve_intertoken_seconds")
    h_ttft = reg.histogram("edl_serve_ttft_seconds")
    h_stall = reg.histogram("edl_serve_prefill_stall_seconds")

    rng = np.random.RandomState(0)
    corpus = model.synth_batch(rng, 64)["tokens"]

    def _failures():
        return (
            m_requests.value(status="error")
            + m_requests.value(status="expired")
            + m_requests.value(status="rejected")
        )

    import jax._src.compiler as _compiler

    m_compiles = reg.counter("edl_xla_compiles_total")
    compiles_before = m_compiles.value()
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    err0 = _failures()
    restarted_mid_swap = [0]
    _compiler.backend_compile = _counting_bc
    try:
        modes = {}
        for mode in ("monolithic", "chunked"):
            batcher = TokenContinuousBatcher(
                engine,
                queue_limit=8192,
                default_deadline_s=120.0,
                chunked_prefill=(mode == "chunked"),
                prefill_token_budget=32,
            ).start()
            # -- steady short-prompt decode load (4 sequences kept in
            # flight by a driver thread for the whole phase pair)
            stop = threading.Event()
            load_tickets = []

            def load_driver():
                i = 0
                inflight = []
                while not stop.is_set():
                    while len(inflight) < 4 and not stop.is_set():
                        plen = 6 + (i * 5) % 20
                        t = batcher.submit_generate(
                            {"tokens": corpus[i % len(corpus)][:plen]},
                            max_new_tokens=24,
                        )
                        load_tickets.append(t)
                        inflight.append(t)
                        i += 1
                    inflight = [
                        t for t in inflight if not t._done.is_set()
                    ]
                    time.sleep(0.001)

            th = threading.Thread(target=load_driver, daemon=True)
            th.start()
            time.sleep(0.3)  # cadence settled
            # -- phase 1: no admissions (baseline inter-token p95)
            it0 = h_intertoken.series()
            time.sleep(1.0)
            base = _hist_delta(h_intertoken.series(), it0)
            base_p95 = histogram_quantile(base, 0.95)
            # -- phase 2: periodic long admissions under the same load
            it1 = h_intertoken.series()
            ttft0 = h_ttft.series()
            stall0 = h_stall.series()
            gen0 = engine.weights_generation
            long_tickets = []
            for j in range(6):
                plen = long_lens[j % len(long_lens)]
                long_tickets.append(
                    batcher.submit_generate(
                        {"tokens": corpus[(7 * j) % len(corpus)][:plen]},
                        max_new_tokens=4,
                    )
                )
                if mode == "chunked" and j == 2:
                    # mid-sweep hot swap: a new verified checkpoint
                    # lands while long prompts are chunking AND the
                    # short load is decoding
                    store.save_async(state_at(100, seed=7))
                    store.wait()
                time.sleep(0.25)
            for t in long_tickets:
                t.result(timeout=240)
            during = _hist_delta(h_intertoken.series(), it1)
            during_p95 = histogram_quantile(during, 0.95)
            ttft = _hist_delta(h_ttft.series(), ttft0)
            stall = _hist_delta(h_stall.series(), stall0)
            stall_p95 = histogram_quantile(stall, 0.95)
            stop.set()
            th.join(timeout=10)
            results = [t.result(timeout=240) for t in load_tickets]
            if mode == "chunked":
                assert engine.weights_generation > gen0, (
                    "mid-sweep hot swap never installed"
                )
                restarted_mid_swap[0] = sum(
                    1 for _, meta in results if meta["restarts"]
                ) + sum(
                    1
                    for t in long_tickets
                    if t.result()[1]["restarts"]
                )
            batcher.stop()
            ratio = (
                round(during_p95 / base_p95, 3)
                if base_p95 and during_p95
                else None
            )
            modes[mode] = {
                "baseline_intertoken_p95_ms": (
                    round(base_p95 * 1000, 3) if base_p95 else None
                ),
                "admission_intertoken_p95_ms": (
                    round(during_p95 * 1000, 3) if during_p95 else None
                ),
                "intertoken_p95_ratio": ratio,
                "long_ttft_p50_ms": (
                    lambda v: round(v * 1000, 3) if v else None
                )(histogram_quantile(ttft, 0.5)),
                "long_ttft_p95_ms": (
                    lambda v: round(v * 1000, 3) if v else None
                )(histogram_quantile(ttft, 0.95)),
                "prefill_stall_p95_ms": (
                    round(stall_p95 * 1000, 3) if stall_p95 else None
                ),
                "long_admissions": len(long_tickets),
                "long_prompt_tokens": list(long_lens),
            }
        dropped = int(_failures() - err0)
        steady_compiles = int(m_compiles.value() - compiles_before)
        assert dropped == 0, f"{dropped} sequences dropped in the sweep"
        assert steady_compiles == 0, (
            f"{steady_compiles} XLA compiles in the interference sweep"
        )
    finally:
        _compiler.backend_compile = _real_bc

    return {
        "model": model.name,
        "max_context": ctx,
        "block_tokens": engine.block_tokens,
        "max_chunk_tokens": engine.max_chunk_tokens,
        "prefill_token_budget": 32,
        "monolithic": modes["monolithic"],
        "chunked": modes["chunked"],
        "hot_swap": {
            "swapped": True,
            "restarted_mid_generation": restarted_mid_swap[0],
        },
        "dropped_sequences": dropped,
        "steady_state_xla_compiles": steady_compiles,
    }


def bench_migrate() -> dict:
    """Live KV sequence migration section (ISSUE 16): repeated drain
    rounds of a replica with a DELIBERATELY long generation in flight,
    each handing the sequence to a surviving replica over the chunked
    TCP push.  Per round: the drain must ack while the generation is
    still decoding on the survivor (drain latency is O(KV transfer),
    not O(generation)), and the migrated sequence's final tokens must
    equal the unmigrated same-seed reference BIT-EXACTLY.  Gated:
    bit_identical == true, dropped == 0, steady-state compiles == 0
    (round 0 warms the import scatter executables), drain p95 under
    the threshold."""
    import time

    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from edl_tpu import telemetry
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import (
        DecodeEngine,
        MigrationReceiver,
        ServingReplica,
        TokenContinuousBatcher,
    )

    on_tpu = jax.default_backend() == "tpu"
    model = get_model("transformer_lm", tiny=not on_tpu)
    opt = optax.adam(1e-3)
    params = model.init_params(jax.random.key(1))
    store = HostDRAMStore()
    store.save_async(
        TrainState(
            step=jnp.asarray(1, jnp.int32),
            params=params,
            opt_state=opt.init(params),
        )
    )
    store.wait()

    def _engine():
        e = DecodeEngine(
            model,
            store,
            devices=jax.devices()[:1],
            max_batch=1,
            max_seqs=4,
            block_tokens=16,
        )
        e.load()
        e.warm()
        return e

    victim_engine = _engine()
    survivor_engine = _engine()
    survivor_b = TokenContinuousBatcher(
        survivor_engine, refresh=False, default_deadline_s=120.0
    ).start()
    receiver = MigrationReceiver(
        survivor_engine, survivor_b, replica_id="bench-survivor"
    ).start()
    dest = f"tcp://127.0.0.1:{receiver.port}"

    prompt = list(range(1, 9))
    max_new = 48

    import jax._src.compiler as _compiler

    reg = telemetry.get_registry()
    m_compiles = reg.counter("edl_xla_compiles_total")
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    rounds = 4  # round 0 warms the export/import executables
    latencies_ms = []
    warmup_ms = None
    tokens_at_ack = []
    migrated_rounds = 0
    drained_all = True
    dropped = 0
    results = []
    compiles_steady_before = None
    _compiler.backend_compile = _counting_bc
    try:
        for n in range(rounds):
            if n == 1:
                compiles_steady_before = m_compiles.value()
            replica = ServingReplica(
                victim_engine,
                replica_id=f"bench-migrate-{n}",
                heartbeat_interval=60.0,
                telemetry_interval=1e9,
            )
            replica.start()
            t = replica.gen_batcher.submit_generate(
                {"tokens": prompt},
                max_new_tokens=max_new,
                deadline_s=120.0,
            )
            # a long generation genuinely mid-flight (and past one KV
            # block, so every round pushes the same block count)
            deadline = time.monotonic() + 30
            while len(t.tokens) < 10 and time.monotonic() < deadline:
                time.sleep(0.002)
            r = replica.drain(budget_s=60.0, migrate_to=dest)
            at_ack = len(t.tokens)
            drained_all = drained_all and bool(r["drained"])
            migrated_rounds += int(
                r.get("progress", {}).get("migrated", 0) == 1
            )
            if n == 0:
                warmup_ms = round(r["seconds"] * 1000.0, 3)
            else:
                latencies_ms.append(round(r["seconds"] * 1000.0, 3))
            tokens_at_ack.append(at_ack)
            tokens, meta = t.result(timeout=120)
            if len(tokens) != max_new:
                dropped += 1
            results.append(list(tokens))
            replica.stop()
    finally:
        _compiler.backend_compile = _real_bc
        survivor_b.stop()
        receiver.stop()
    steady_compiles = int(m_compiles.value() - compiles_steady_before)

    # Unmigrated same-seed reference (compiled OUTSIDE the seam): the
    # greedy decode the migrated tokens must equal bit-for-bit.
    spec = model.decode
    eng = victim_engine
    kp = jnp.zeros(
        (
            spec.layers,
            eng.blocks_per_seq + 1,
            eng.block_tokens,
            spec.heads,
            spec.head_dim,
        ),
        spec.cache_dtype,
    )
    vp = jnp.zeros_like(kp)
    tab = np.arange(1, eng.blocks_per_seq + 1, dtype=np.int32)[None]
    plen = len(prompt)
    tok = np.zeros((1, eng.prompt_bucket_for(plen)), np.int32)
    tok[0, :plen] = prompt
    ids, kp, vp = jax.jit(spec.prefill_fn)(
        params, tok, np.asarray([plen], np.int32), kp, vp, tab
    )
    ref = [int(ids[0])]
    ln = np.asarray([plen], np.int32)
    dec = jax.jit(spec.decode_fn)
    while len(ref) < max_new:
        ids, kp, vp = dec(
            params, np.asarray([ref[-1]], np.int32), ln, kp, vp, tab
        )
        ref.append(int(ids[0]))
        ln = ln + 1
    bit_identical = all(toks == ref for toks in results)

    assert drained_all, "a bench drain missed its budget"
    assert dropped == 0, f"{dropped} sequences dropped across migrations"
    assert bit_identical, "migrated tokens diverged from the reference"
    assert migrated_rounds == rounds, "a round fell off the migrate path"
    ordered = sorted(latencies_ms)
    return {
        "rounds": rounds,
        "max_new_tokens": max_new,
        "drain_latency_ms": latencies_ms,
        "warmup_round_ms": warmup_ms,
        "drain_latency_p50_ms": ordered[len(ordered) // 2],
        "drain_latency_p95_ms": ordered[-1],
        "tokens_at_ack": tokens_at_ack,
        "ack_before_generation_end": all(
            a < max_new for a in tokens_at_ack
        ),
        "migrated_rounds": migrated_rounds,
        "bit_identical": bit_identical,
        "dropped": dropped,
        "drained_all": drained_all,
        "steady_state_xla_compiles": steady_compiles,
    }


def bench_prefix() -> dict:
    """Content-addressed KV prefix cache section (ISSUE 17): N
    sessions share one long system prompt with divergent tails — the
    traffic shape prefix caching exists for — decoded twice over the
    same engine and prompts: COLD (``prefix_cache=False``, every
    admission prefills from token 0) then WARM (``prefix_cache=True``,
    admissions skip straight to the first cold block).  Published:
    per-phase TTFT p50/p95 (exact per-request values, not histogram
    buckets), the warm/cold TTFT p95 ratio, the warm phase's hit
    ratio / reused blocks, and the cross-phase bit-identity of every
    session's tokens.  Gated: warm_vs_cold_ttft_p95_ratio <= 0.5,
    hit_ratio >= 0.9, steady-state compiles == 0, dropped == 0."""
    import time

    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from edl_tpu import telemetry
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import DecodeEngine, TokenContinuousBatcher

    on_tpu = jax.default_backend() == "tpu"
    # The long-context family is the shared-system-prompt shape: the
    # prefix covers 3/4 of the window and the per-user tail is small.
    model = get_model("longcontext_lm", tiny=not on_tpu)
    params = model.init_params(jax.random.key(1))
    opt = optax.adam(1e-3)
    store = HostDRAMStore()
    store.save_async(
        TrainState(
            step=jnp.asarray(1, jnp.int32),
            params=params,
            opt_state=opt.init(params),
        )
    )
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
        max_chunk_tokens=32,
    )
    engine.load()
    engine.warm()
    bt = engine.block_tokens
    shared_tokens = (engine.max_context * 3 // 4 // bt) * bt  # block-aligned
    tail_tokens = bt // 2
    sessions = 12
    max_new = 4

    rng = np.random.RandomState(17)
    corpus = model.synth_batch(rng, sessions + 1)["tokens"]
    shared = list(int(x) for x in corpus[0][:shared_tokens])
    prompts = [
        shared + [int(x) for x in corpus[1 + i][:tail_tokens]]
        for i in range(sessions)
    ]

    import jax._src.compiler as _compiler

    reg = telemetry.get_registry()
    m_compiles = reg.counter("edl_xla_compiles_total")
    compiles_before = m_compiles.value()
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    def _phase(batcher):
        """Sequential sessions (each TTFT isolated from queueing) ->
        (per-session tokens, per-session ttft seconds, dropped)."""
        toks, ttfts, dropped = [], [], 0
        try:
            for p in prompts:
                t = batcher.submit_generate(
                    {"tokens": p},
                    max_new_tokens=max_new,
                    deadline_s=120.0,
                )
                tokens, meta = t.result(timeout=120)
                if len(tokens) != max_new or meta["ttft_s"] is None:
                    dropped += 1
                toks.append(list(tokens))
                ttfts.append(meta["ttft_s"])
        finally:
            batcher.stop()
        return toks, ttfts, dropped

    def _q(vals, q):
        ordered = sorted(v for v in vals if v is not None)
        if not ordered:
            return None
        return ordered[min(len(ordered) - 1, int(q * (len(ordered) - 1)))]

    _compiler.backend_compile = _counting_bc
    try:
        cold_toks, cold_ttft, cold_drop = _phase(
            TokenContinuousBatcher(
                engine,
                refresh=False,
                default_deadline_s=120.0,
                prefix_cache=False,
            ).start()
        )
        warm_b = TokenContinuousBatcher(
            engine, refresh=False, default_deadline_s=120.0
        ).start()
        warm_toks, warm_ttft, warm_drop = _phase(warm_b)
        stats = dict(warm_b.prefix.stats)
        steady_compiles = int(m_compiles.value() - compiles_before)
    finally:
        _compiler.backend_compile = _real_bc

    dropped = cold_drop + warm_drop
    bit_identical = warm_toks == cold_toks
    hit_ratio = stats["hits"] / max(1, stats["hits"] + stats["misses"])
    cold_p95 = _q(cold_ttft, 0.95)
    warm_p95 = _q(warm_ttft, 0.95)
    ratio = (
        round(warm_p95 / cold_p95, 4) if cold_p95 and warm_p95 else None
    )
    assert dropped == 0, f"{dropped} sessions dropped in the prefix bench"
    assert bit_identical, "warm (reused-block) tokens diverged from cold"
    assert steady_compiles == 0, (
        f"{steady_compiles} XLA compiles on the warm admission path"
    )
    return {
        "model": model.name,
        "sessions": sessions,
        "shared_prompt_tokens": shared_tokens,
        "tail_tokens": tail_tokens,
        "max_new_tokens": max_new,
        "block_tokens": bt,
        "cold": {
            "ttft_p50_ms": round(_q(cold_ttft, 0.5) * 1000, 3),
            "ttft_p95_ms": round(cold_p95 * 1000, 3),
        },
        "warm": {
            "ttft_p50_ms": round(_q(warm_ttft, 0.5) * 1000, 3),
            "ttft_p95_ms": round(warm_p95 * 1000, 3),
            "hit_ratio": round(hit_ratio, 4),
            "hits": stats["hits"],
            "misses": stats["misses"],
            "blocks_reused": stats["blocks_reused"],
            "evictions": stats["evictions"],
        },
        "warm_vs_cold_ttft_p95_ratio": ratio,
        "bit_identical": bit_identical,
        "dropped": dropped,
        "steady_state_xla_compiles": steady_compiles,
    }


# ---------------------------------------------------------------------------
# ISSUE 18: tensor-parallel decode — tp=1 vs tp=2 on a model exceeding
# tp=1's per-device budget
# ---------------------------------------------------------------------------


def bench_tp() -> dict:
    """Tensor-parallel decode A/B (ISSUE 18): the SAME model served at
    tp=1 (one device holds everything) vs tp=2 (attention heads and the
    KV pools' head axis shard across two devices).

    The capacity claim needs a model that does NOT fit one device's
    budget: CPU has no real HBM ceiling, so the bench imposes an
    artificial per-device byte cap sized between the two measured
    footprints — tp=1's per-device bytes exceed it (the model cannot
    serve), tp=2's fit (it can).  Alongside: tokens/s and TTFT at both
    shapes, greedy tokens asserted bit-identical across tp, ZERO
    steady-state compiles at the backend_compile seam, and the
    hot-swap staging bill — per-device weight bytes <= 0.6x the full
    state (exactly the tp-sharded kernels at 1/2 plus the replicated
    layernorms/biases/embedding-position leaves).

    Runs in a hermetic 2-virtual-CPU-device child (the parent bench
    process may own a single chip)."""
    import os
    import subprocess
    import sys

    from edl_tpu.utils.hermetic import virtual_cpu_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "bench_lib.serving", "--tp-child"],
        env=virtual_cpu_env(2),
        capture_output=True,
        text=True,
        timeout=900,
        cwd=repo,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"tp bench child rc={proc.returncode}: {proc.stderr[-2000:]}"
        )
    import json

    return json.loads(proc.stdout.strip().splitlines()[-1])


def _tp_measure() -> dict:
    """Child body: both engines, one process, 2 forced CPU devices."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState
    from edl_tpu.serving import DecodeEngine

    assert len(jax.devices()) >= 2, jax.devices()
    model = get_model("transformer_lm", tiny=True)
    opt = optax.adam(1e-3)

    def state_at(step: int, seed: int = 0) -> TrainState:
        p = model.init_params(jax.random.key(seed))
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            params=p,
            opt_state=opt.init(p),
        )

    store = HostDRAMStore()
    store.save_async(state_at(1))
    store.wait()

    import jax._src.compiler as _compiler

    _real_bc = _compiler.backend_compile
    count = {"n": 0}

    def _counting_bc(*args, **kwargs):
        count["n"] += 1
        return _real_bc(*args, **kwargs)

    n_new = 32
    prompt = np.arange(3, 3 + 24, dtype=np.int32)

    def run(tp: int, ndev: int):
        engine = DecodeEngine(
            model,
            store,
            devices=jax.devices()[:ndev],
            max_batch=max(1, ndev // tp),
            max_seqs=4,
            block_tokens=16,
            tp=tp,
        )
        assert engine.load()
        engine.warm()
        w = engine.current_weights()
        tab = np.asarray(
            engine.pool.alloc(engine.blocks_per_seq), np.int32
        )
        t0 = time.perf_counter()
        first = int(engine.prefill(w, prompt, tab))
        ttft_s = time.perf_counter() - t0
        out = [first]
        ln = np.asarray([len(prompt)], np.int32)
        count["n"] = 0
        _compiler.backend_compile = _counting_bc
        try:
            t1 = time.perf_counter()
            while len(out) < n_new:
                ids = engine.decode_step(
                    w, np.asarray([out[-1]], np.int32), ln, tab[None]
                )
                out.append(int(ids[0]))
                ln = ln + 1
            decode_s = time.perf_counter() - t1
        finally:
            _compiler.backend_compile = _real_bc
        # hot swap on the sharded placement: stage a NEW generation and
        # verify the install lands (each device stages only its shard)
        store.save_async(state_at(100 + tp, seed=tp))
        store.wait()
        gen0 = engine.weights_generation
        assert engine.refresh(), "hot swap did not install"
        assert engine.weights_generation > gen0
        w_shard = engine.weight_shard_bytes_per_device()
        kv_dev = engine.kv_pool_bytes_per_device()
        info = {
            "devices": ndev,
            "bytes_per_device": int(w_shard + kv_dev),
            "weight_shard_bytes_per_device": int(w_shard),
            "kv_pool_bytes_per_device": int(kv_dev),
            "weight_full_bytes": int(engine.weight_full_bytes()),
            "ttft_ms": round(ttft_s * 1000, 3),
            "tokens_per_s": round((n_new - 1) / decode_s, 1),
            "steady_state_xla_compiles": count["n"],
        }
        return out, info

    t1_tokens, tp1 = run(1, 1)
    t2_tokens, tp2 = run(2, 2)
    # the artificial per-device budget: between the two footprints, so
    # "does not fit at tp=1, fits at tp=2" is a measured statement
    cap = (tp1["bytes_per_device"] + tp2["bytes_per_device"]) // 2
    tp1["fits"] = tp1["bytes_per_device"] <= cap
    tp2["fits"] = tp2["bytes_per_device"] <= cap
    assert not tp1["fits"] and tp2["fits"], (tp1, tp2, cap)
    bit_identical = t1_tokens == t2_tokens
    assert bit_identical, (t1_tokens, t2_tokens)
    steady = tp1["steady_state_xla_compiles"] + tp2["steady_state_xla_compiles"]
    assert steady == 0, f"{steady} XLA compiles on the steady tp path"
    swap_ratio = round(
        tp2["weight_shard_bytes_per_device"] / tp2["weight_full_bytes"], 4
    )
    return {
        "model": model.name,
        "prompt_tokens": int(prompt.shape[0]),
        "new_tokens": n_new,
        "hbm_cap_bytes_per_device": int(cap),
        "tp1": tp1,
        "tp2": tp2,
        "bit_identical": bit_identical,
        "steady_state_xla_compiles": int(steady),
        "tokens_per_s_tp2_vs_tp1": round(
            tp2["tokens_per_s"] / max(tp1["tokens_per_s"], 1e-9), 3
        ),
        # the hot-swap staging bill: what ONE device pulls on a weight
        # swap, as a fraction of the full state (1/tp for sharded
        # kernels; replicated layernorm/bias leaves keep it above 0.5)
        "swap_bytes_per_device_ratio": swap_ratio,
    }


if __name__ == "__main__":
    import sys as _sys

    if "--tp-child" in _sys.argv:
        import json as _json

        from edl_tpu.utils.hermetic import pin_cpu_platform

        pin_cpu_platform()
        print(_json.dumps(_tp_measure()))
