"""bench LM sections — per-model training-step throughput.

ROADMAP item 5's per-module split, final tranche: the shared
``timed_train_loop`` harness plus every per-model section that used it
from the monolithic ``bench.py`` (transformer_base, mnist, the
long-context ladder, MoE).  ``bench.py`` stays the driver that
composes these into the ONE JSON round record.

The long-context and MoE sections run in fresh subprocesses of THIS
module: a second process sharing the (tunneled) chip time-slices it
and inflates the measured step ~70%, so each heavyweight model owns
the chip alone and the parent must not have initialized a TPU client
before spawning.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V5E_BF16_PEAK_PER_CHIP = 197e12


def timed_train_loop(model, batch_size: int, steps: int) -> dict:
    """Shared measurement harness: compile-warm, pre-staged device
    batches, float(loss) sync at the timing boundaries.

    Pre-staging matters on a tunneled platform where each
    host->device transfer blocks ~15ms and would pollute the compute
    number (production pipelines prefetch/overlap; the resize bench
    covers the data path separately).  The float(loss) sync matters
    because block_until_ready returns before device completion on the
    tunnel and wildly under-measures."""
    import time

    import jax
    import optax

    from edl_tpu.parallel.mesh import dp_mesh
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.train import Trainer

    n_dev = len(jax.devices())
    mesh = dp_mesh(n_dev)
    trainer = Trainer(model, optax.adamw(1e-4), mesh)
    state = trainer.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(model.synth_batch, max(64, 2 * batch_size)),
        global_batch_size=batch_size,
    )
    batches = [data.device_batch(s, mesh) for s in range(steps + 1)]
    jax.block_until_ready(batches)
    state, metrics = trainer.step(state, batches[0])  # compile warm-up
    float(metrics["loss"])
    t0 = time.perf_counter()
    for s in range(1, steps + 1):
        state, metrics = trainer.step(state, batches[s])
    float(metrics["loss"])  # sync: the whole chain must have executed
    dt = (time.perf_counter() - t0) / steps
    on_tpu = jax.default_backend() == "tpu"
    peak = V5E_BF16_PEAK_PER_CHIP * n_dev
    # Trained tokens/example comes from the MODEL, not a caller-passed
    # constant that could silently diverge from the actual shapes
    # (ADVICE r3); fall back to the widest batch dim for token models
    # registered without the field.
    seq_len = model.tokens_per_example or max(
        (v.shape[1] for v in batches[0].values() if v.ndim >= 2), default=1
    )
    out = {
        "step_s": dt,
        "examples_per_s": batch_size / dt,
        "tokens_per_s": batch_size * seq_len / dt,
        "mfu": model.flops_per_example * batch_size / dt / peak
        if on_tpu
        else 0.0,
        "batch": batch_size,
        "seq_len": seq_len,
    }
    # Model-specific quality counters ride along (e.g. the MoE family's
    # capacity-drop rate — an MFU figure must not hide dropped compute).
    for k, v in metrics.items():
        if k.startswith("moe_"):
            out[k] = round(float(v), 5)
    return out


def bench_transformer_throughput(steps: int = 20) -> dict:
    """Flagship transformer-base training-step throughput on the local
    device(s): tokens/s and MFU vs v5e bf16 peak (197 TFLOP/s/chip)."""
    import jax

    from edl_tpu.models.base import get_model

    n_dev = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    model = get_model("transformer_base", tiny=not on_tpu)
    batch_size = 64 * n_dev if on_tpu else 2 * n_dev
    return timed_train_loop(model, batch_size, steps)


def bench_mnist_throughput(steps: int = 20) -> dict:
    """MNIST ConvNet training-step throughput — the BASELINE config 1/2
    model finally gets published numbers (VERDICT r5 #8): step_s and
    examples/s on the local device(s)."""
    import jax

    from edl_tpu.models.base import get_model

    n_dev = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    batch = (256 if on_tpu else 32) * n_dev
    r = timed_train_loop(get_model("mnist"), batch, steps)
    # images, not tokens: report examples/s and drop the LM-shaped keys
    return {
        "step_s": round(r["step_s"], 5),
        "examples_per_s": round(r["examples_per_s"], 1),
        "batch": r["batch"],
    }


def bench_longcontext_lm(seq_len: int = 2048, batch: int = 8, steps: int = 8) -> dict:
    """Decoder-only LM at long context on the Pallas flash-attention
    path (XLA's fused attention OOMs here: its [B, H, T, T] f32 scores
    alone exceed HBM at training batch sizes).  Evidence for the
    long-context capability bar (SURVEY.md §5.7 — absent in the 2018
    reference; first-class in the rebuild).

    Runs in a fresh subprocess BEFORE any other section initializes the
    TPU in this process: a second process sharing the (tunneled) chip
    time-slices it and inflates this model's step ~70%.  The parent
    must not import jax before spawning."""
    return run_bench_child(
        "--longcontext-child", str(seq_len), str(batch), str(steps)
    )


def _longcontext_child(seq_len: int, batch: int, steps: int):
    import jax

    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "flash path is TPU-only"}))
        return
    from edl_tpu.models.base import get_model

    model = get_model("transformer_lm", seq_len=seq_len)
    print(json.dumps(timed_train_loop(model, batch, steps)))


def bench_moe_lm(batch: int = 8, steps: int = 8, group: int = 0) -> dict:
    """Full-size MoE LM (12L x 8 experts, T=2048, grouped top-1
    routing) — the expert-parallel family's single-chip figure (MFU is
    ACTIVE FLOPs: one expert per token plus routing einsums).  Child
    process for the same chip-isolation reason as long context.
    ``group`` overrides the routing group width (0 = model default)."""
    return run_bench_child("--moe-child", str(batch), str(steps), str(group))


def _moe_child(batch: int, steps: int, group: int = 0):
    import jax

    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "full-size MoE bench is TPU-only"}))
        return
    from edl_tpu.models.base import get_model

    kwargs = {"group_size": group} if group else {}
    out = timed_train_loop(get_model("moe_lm", **kwargs), batch, steps)
    print(json.dumps(out))


def run_bench_child(*argv: str, module: str = "bench_lib.lm", env=None) -> dict:
    """Spawn a bench-section child (``python -m <module> <argv>``) and
    parse the JSON line it prints last (warnings go to stderr, so the
    parse is safe)."""
    proc = subprocess.run(
        [sys.executable, "-m", module, *argv],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
        cwd=REPO,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{argv[0]} subprocess rc={proc.returncode}: "
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def lm_summary(r: dict) -> dict:
    """Per-model bench summary (one shape for every LM section); error
    and skipped records pass through untouched.  Model-specific quality
    counters (the ``moe_`` keys, e.g. the capacity-drop rate) pass
    through too: an MFU figure must not hide dropped compute, and
    stripping them here was how the r5 record lost the MoE drop rate
    (VERDICT r5)."""
    if "error" in r or "skipped" in r:
        return r
    out = {
        "step_s": round(r["step_s"], 5),
        "tokens_per_s": round(r["tokens_per_s"]),
        "mfu": round(r["mfu"], 4),
        "batch": r["batch"],
        "seq_len": r["seq_len"],
    }
    out.update({k: v for k, v in r.items() if k.startswith("moe_")})
    return out


if __name__ == "__main__":
    if "--longcontext-child" in sys.argv:
        i = sys.argv.index("--longcontext-child")
        sl, b, st = (int(x) for x in sys.argv[i + 1 : i + 4])
        _longcontext_child(sl, b, st)
    elif "--moe-child" in sys.argv:
        i = sys.argv.index("--moe-child")
        rest = [int(x) for x in sys.argv[i + 1 :][:3]]
        _moe_child(*rest)
