"""bench restore_paths — joiner restore paths, measured side by side.

Two halves:

- ``run_restore_paths``: the PR 2 section (moved here per ROADMAP
  item 5's per-module rule): local vs streamed vs retired-monolithic
  vs delta restore at transformer scale on a REAL 2-process CPU world
  (gloo) — the numbers that keep the broadcast retirement a measured
  claim.
- ``run_fabric_sweep``: the ROADMAP item 3 claim — multi-source
  parallel fabric restore vs the single-source stream, swept to
  >= 2GB of simulated state.  One joiner pulls the full state either
  from ONE serving peer (PR 2's stream) or from N peers in parallel
  (the shard fabric); both move real bytes over real loopback TCP
  with per-chunk CRCs, so the ratio is transport against transport.
  The sweep runs in a hermetic subprocess (multi-GB allocations must
  not bloat the bench driver), and the gate
  (``restore_paths.fabric_sweep.largest.multi_vs_single_speedup``)
  asserts the parallel fabric beats the single NIC-path >= 3x at the
  largest state point.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# PR 2 section: 2-process gloo world, four restore paths
# ---------------------------------------------------------------------------


def run_restore_paths() -> dict:
    """Joiner-only vs transfer restore at TRANSFORMER scale, measured
    on a real 2-process CPU world (gloo) — the numbers that make the
    <60s resize budget an extrapolation from measured state sizes
    rather than from fit_a_line (VERDICT r4 weak-8 / next-10).

    local      = every member holds the digest-agreed checkpoint and
                 restores from its own DRAM (no cross-pod state motion);
    broadcast  = one member is a fresh joiner, so the holder STREAMS it
                 the full state (chunked delta transfer — the path that
                 retired the r05 monolithic broadcast);
    monolithic = the retired r05 broadcast_one_to_all path, kept
                 measured side by side so the retirement stays a
                 benchmarked claim;
    delta      = one member diverged in a single leaf, so only that
                 leaf moves."""
    import socket

    # Bind port 0 in the parent and hand the free port to both ranks:
    # a hard-coded port collides with a stale child (or anything else)
    # from a previous run and fails the whole section.
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = []
    try:
        for rank in (0, 1):
            env = dict(os.environ)
            flags = [
                f
                for f in env.get("XLA_FLAGS", "").split()
                if "--xla_force_host_platform_device_count" not in f
            ]
            env["XLA_FLAGS"] = " ".join(flags)
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "bench_lib.restore",
                        "--restore-child",
                        str(rank),
                        str(port),
                    ],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    cwd=REPO,
                )
            )
        # The SAME generous timeout for both ranks: rank 1 does real
        # work (it is the receiver in every transfer measurement) and
        # a short rank-1 timeout used to kill the bench under CI load.
        out0, err0 = procs[0].communicate(timeout=900)
        _, err1 = procs[1].communicate(timeout=900)
        # BOTH ranks must exit clean: rank 1 can fail its own invariant
        # after rank 0 already printed (the collective completed for
        # rank 0 first) — a one-rank failure must not report a clean
        # benchmark.
        for rank, (rc, err) in enumerate(
            [(procs[0].returncode, err0), (procs[1].returncode, err1)]
        ):
            if rc != 0:
                raise RuntimeError(
                    f"restore child rank {rank} rc={rc}: {err[-2000:]}"
                )
        record = json.loads(out0.strip().splitlines()[-1])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    record["fabric_sweep"] = run_fabric_sweep()
    return record


def _restore_child(rank: int, port: int):
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=rank,
        initialization_timeout=60,
    )
    import optax

    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.checkpoint import transfer as tx
    from edl_tpu.models.base import get_model
    from edl_tpu.parallel.mesh import dp_mesh
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer
    from edl_tpu.runtime.train import Trainer

    def worldwide_max(seconds: float) -> float:
        """A transfer is only done when its RECEIVER is done: report
        the slowest rank's wall time, not rank 0's (the source returns
        early — it serves from a background thread)."""
        from jax.experimental import multihost_utils

        times = multihost_utils.process_allgather(
            np.asarray([seconds], np.float64)
        )
        return float(np.max(times))

    model = get_model("transformer_base")  # full size: the real state mass
    mesh = dp_mesh(2)
    trainer = Trainer(model, optax.adam(1e-4), mesh)
    state = trainer.init_state()
    coord = LocalCoordinator(target_world=2, max_world=2)
    data = ShardedDataIterator(
        synthetic_dataset(model.synth_batch, 64), global_batch_size=64
    )
    et = ElasticTrainer(
        model, optax.adam(1e-4), data, coord, store=HostDRAMStore()
    )
    et.generation = 1
    et.store.save_async(state, generation=1)
    et.store.wait()
    state_mb = et.store.latest().nbytes() / 1e6

    # Path 1: every member holds the identical checkpoint -> local.
    t0 = time.perf_counter()
    st, step, source, _ = et._restore_multiprocess(trainer)
    jax.block_until_ready(st)
    local_s = worldwide_max(time.perf_counter() - t0)
    assert source == "local", source

    # Path 2 (the RETIRED r05 path, measured end to end for the
    # side-by-side): one monolithic broadcast_one_to_all of every
    # leaf, then the adoption + placement the old
    # _restore_multiprocess did — store.put (full digest re-hash) and
    # store.restore (second host materialization + device placement).
    from edl_tpu.checkpoint import HostCheckpoint

    abstract = jax.eval_shape(
        trainer._init_fn, jax.random.key(trainer.seed)
    )
    leaves_abs, treedef = jax.tree_util.tree_flatten(abstract)
    scratch_store = HostDRAMStore()
    t0 = time.perf_counter()
    mono = tx.monolithic_broadcast_restore(
        leaves_abs, et.store.latest(), is_source=rank == 0
    )
    merged = HostCheckpoint(
        step=0, generation=1, leaves=mono, treedef=treedef
    )
    merged.step = int(np.asarray(merged.unflatten().step))
    scratch_store.put(merged)
    mono_state = scratch_store.restore(merged, trainer.mesh, None)
    jax.block_until_ready(mono_state)
    monolithic_s = worldwide_max(time.perf_counter() - t0)
    assert sum(x.nbytes for x in mono) == et.store.latest().nbytes()
    del mono, merged, mono_state, scratch_store

    # Path 3: rank 1 lost its store (a fresh joiner) -> the full state
    # streams from rank 0.  A 2-process world has ONE holder, so the
    # fabric deterministically routes to the single-source stream —
    # this figure IS the single-NIC baseline the fabric sweep beats.
    if rank == 1:
        et.store._checkpoints.clear()
    t0 = time.perf_counter()
    st, step, source, stats = et._restore_multiprocess(trainer)
    jax.block_until_ready(st)
    broadcast_s = worldwide_max(time.perf_counter() - t0)
    assert source == "broadcast", source

    # Path 4: rank 1 diverged in ONE leaf (stale store) -> the delta
    # agreement moves only that leaf.
    delta_mb = 0.0
    if rank == 1:
        ck = et.store.latest()
        big = max(range(len(ck.leaves)), key=lambda i: ck.leaves[i].nbytes)
        leaf = np.array(ck.leaves[big], copy=True)
        leaf.reshape(-1).view(np.uint8)[0] ^= 0xFF
        ck.leaves[big] = leaf
        delta_mb = leaf.nbytes / 1e6
        # Honest re-advertisement: the member KNOWS its bytes changed.
        ck._digest = None
        ck._leaf_digests = None
        ck._shard_digests = None
    t0 = time.perf_counter()
    st, step, source, stats = et._restore_multiprocess(trainer)
    jax.block_until_ready(st)
    delta_s = worldwide_max(time.perf_counter() - t0)
    moved_mb = worldwide_max(
        (stats or {}).get("bytes_received", 0) / 1e6
    )
    # Both sides touched the wire: rank 1 received the one diverged
    # leaf, rank 0 served it.
    assert source == "broadcast", source
    # THE delta claim this section exists to publish: only the one
    # diverged leaf moved, not the full state.  A regression to
    # full-state transfer must fail the bench, not ship a silently
    # inflated delta_moved_mb.
    diverged_mb = worldwide_max(delta_mb)
    assert abs(moved_mb - diverged_mb) < 1.0, (moved_mb, diverged_mb)

    if rank == 0:
        print(
            json.dumps(
                {
                    "state_mb": round(state_mb, 1),
                    "local_restore_s": round(local_s, 4),
                    "broadcast_restore_s": round(broadcast_s, 4),
                    "monolithic_restore_s": round(monolithic_s, 4),
                    "speedup_vs_monolithic": round(
                        monolithic_s / max(broadcast_s, 1e-9), 2
                    ),
                    "delta_restore_s": round(delta_s, 4),
                    "delta_moved_mb": round(moved_mb, 1),
                    "chunk_mb": 64,
                    "processes": 2,
                }
            )
        )


# ---------------------------------------------------------------------------
# ROADMAP item 3: multi-source fabric vs single-source stream, to 2GB
# ---------------------------------------------------------------------------

#: swept simulated state sizes; the LARGEST point carries the >= 3x
#: threshold gate
SWEEP_STATE_BYTES = (256 << 20, 1 << 30, 2 << 30)
SWEEP_SOURCES = 4


def run_fabric_sweep(
    state_bytes=SWEEP_STATE_BYTES, sources: int = SWEEP_SOURCES
) -> dict:
    """Parent half: run the sweep in a hermetic subprocess so the
    multi-GB state never lives in the bench driver."""
    spec = json.dumps({"sizes": list(state_bytes), "sources": sources})
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "bench_lib.restore",
            "--fabric-sweep-child",
            spec,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
        cwd=REPO,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"fabric sweep child rc={proc.returncode}: "
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _synthetic_leaves(total_bytes: int, n_leaves: int = 16):
    """~``total_bytes`` of float32 leaves, filled at memset speed from
    a tiled random block (contents are irrelevant to transport cost;
    distinct per leaf so per-leaf digests differ)."""
    import numpy as np

    per = total_bytes // n_leaves // 4
    rows = max(1, per // 1024)
    leaves = []
    rng = np.random.RandomState(7)
    for i in range(n_leaves):
        arr = np.empty((rows, 1024), np.float32)
        pat = rng.standard_normal(1024).astype(np.float32) + i
        arr[:] = pat
        leaves.append(arr)
    return leaves


def _fabric_sweep_child(spec_json: str):
    import threading

    import numpy as np

    import jax

    from edl_tpu.checkpoint import transfer as tx
    from edl_tpu.checkpoint import fabric as fab

    spec = json.loads(spec_json)
    sources = int(spec["sources"])
    points = []

    def make_ckpt(leaves, step):
        _, treedef = jax.tree_util.tree_flatten(list(leaves))
        from edl_tpu.checkpoint.hostdram import HostCheckpoint

        return HostCheckpoint(
            step=step, generation=1, leaves=list(leaves), treedef=treedef
        )

    def run_world(member_fns):
        world = tx.LoopbackWorld(len(member_fns))
        results = [None] * len(member_fns)
        errors = [None] * len(member_fns)

        def runner(rank, fn):
            try:
                results[rank] = fn(world.fabric(rank))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors[rank] = e

        threads = [
            threading.Thread(target=runner, args=(r, fn), daemon=True)
            for r, fn in enumerate(member_fns)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), "sweep member hung"
        for e in errors:
            if e is not None:
                raise e
        return results, time.perf_counter() - t0

    for total in spec["sizes"]:
        leaves = _synthetic_leaves(int(total))
        real_total = sum(l.nbytes for l in leaves)
        template = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
        rows = [l.shape[0] for l in leaves]
        layout = fab.ShardLayout.build(
            [l.nbytes for l in leaves], sources + 1, rows=rows
        )
        # Source checkpoints SHARE the leaf arrays (zero-copy, as N
        # real hosts would each hold their own identical copy); warm
        # every digest OUTSIDE the timed window — production prewarms
        # them on the flush's background thread (stage B).
        cks = [make_ckpt(leaves, step=10) for _ in range(sources)]
        for ck in cks:
            ck.leaf_digests()
            ck.shard_digests(layout)

        # Single-source stream (the PR 2 path: one serving NIC).
        _, single_s = run_world(
            [
                lambda f: tx.stream_restore(f, template, cks[0]),
                lambda f: tx.stream_restore(f, template, None),
            ]
        )

        # Multi-source fabric: one joiner, ``sources`` serving peers.
        fns = [
            (
                lambda f, ck=ck: fab.fabric_restore(
                    f, template, ck, rows=rows
                )
            )
            for ck in cks
        ]
        fns.append(
            lambda f: fab.fabric_restore(f, template, None, rows=rows)
        )
        results, multi_s = run_world(fns)
        joiner = results[-1]
        assert joiner.stats.mode == "fabric", joiner.stats.mode
        assert joiner.stats.bytes_received == real_total
        per_peer = joiner.stats.per_peer or {}
        assert len(per_peer) >= 2
        assert max(per_peer.values()) < real_total
        # Bit-exactness at 2GB, not just timing: spot-check one leaf.
        np.testing.assert_array_equal(
            np.asarray(joiner.leaves[0]), leaves[0]
        )
        points.append(
            {
                "state_mb": round(real_total / 1e6, 1),
                "single_source_s": round(single_s, 4),
                "multi_source_s": round(multi_s, 4),
                "multi_vs_single_speedup": round(
                    single_s / max(multi_s, 1e-9), 2
                ),
                "peers": len(per_peer),
                "per_peer_mb": {
                    k: round(v / 1e6, 1) for k, v in sorted(per_peer.items())
                },
            }
        )
        del leaves, cks, results, joiner
    out = {
        "sources": sources,
        "shard_mb": fab.DEFAULT_SHARD_BYTES >> 20,
        "points": points,
        "largest": points[-1],
    }
    print(json.dumps(out))


# ---------------------------------------------------------------------------
# ISSUE 19: shard-only cluster memory vs the retired full-copy residency
# ---------------------------------------------------------------------------

#: simulated state for the shard-only figure; big enough that the
#: per-member ratio is about layout arithmetic, not fixed overheads
SHARD_ONLY_STATE_BYTES = 256 << 20
SHARD_ONLY_WORLD = 5  # 4 shard-resident peers + 1 empty joiner
SHARD_ONLY_K = 1


def run_shard_only(
    state_bytes: int = SHARD_ONLY_STATE_BYTES,
    world: int = SHARD_ONLY_WORLD,
    k: int = SHARD_ONLY_K,
) -> dict:
    """Parent half: run the shard-only memory figure in a hermetic
    subprocess (hundreds of MB of simulated state must not live in the
    bench driver)."""
    spec = json.dumps(
        {"total": int(state_bytes), "world": int(world), "k": int(k)}
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "bench_lib.restore",
            "--shard-only-child",
            spec,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
        cwd=REPO,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"shard-only child rc={proc.returncode}: "
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _shard_only_child(spec_json: str):
    """The ISSUE 19 memory claim, measured: a world restores with NO
    member holding full state.  Each member's peak host checkpoint
    bytes are its own GSPMD slice + K ring-buddy shards + ONE in-flight
    shard buffer (``shard_restore`` pulls into per-shard buffers), vs
    the retired full-copy residency where EVERY member held the whole
    digest-agreed checkpoint.  The joiner's wire bytes are its wanted
    ranges, not the state.  Gated: ``peak_member_bytes_ratio`` <= 0.6
    at world >= 4 (at W=5/K=1 the layout puts (1+K)/W = 0.4 of the
    state on each member), ``joiner_wire_ratio`` <= 0.55,
    ``bit_identical`` true."""
    import threading
    import zlib

    import numpy as np

    import jax

    from edl_tpu.checkpoint import fabric as fab
    from edl_tpu.checkpoint import transfer as tx
    from edl_tpu.checkpoint.hostdram import HostCheckpoint

    spec = json.loads(spec_json)
    W, K = int(spec["world"]), int(spec["k"])
    shard_b = 4 << 20  # small shards: even 256MB spreads over the ring
    leaves = _synthetic_leaves(int(spec["total"]))
    total = sum(l.nbytes for l in leaves)
    template = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    rows = [l.shape[0] for l in leaves]
    layout = fab.ShardLayout.build(
        [l.nbytes for l in leaves], W, k=K, shard_bytes=shard_b, rows=rows
    )

    def run_world(member_fns):
        world = tx.LoopbackWorld(len(member_fns))
        results = [None] * len(member_fns)
        errors = [None] * len(member_fns)

        def runner(rank, fn):
            try:
                results[rank] = fn(world.fabric(rank))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors[rank] = e

        threads = [
            threading.Thread(target=runner, args=(r, fn), daemon=True)
            for r, fn in enumerate(member_fns)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), "shard-only member hung"
        for e in errors:
            if e is not None:
                raise e
        return results, time.perf_counter() - t0

    # --- retired full-copy residency, measured side by side: every
    # member holds the whole checkpoint, the joiner pulls ALL of it.
    _, treedef = jax.tree_util.tree_flatten(list(leaves))
    cks = [
        HostCheckpoint(
            step=10, generation=1, leaves=list(leaves), treedef=treedef
        )
        for _ in range(W - 1)
    ]
    for ck in cks:
        ck.leaf_digests()
        ck.shard_digests(layout)
    fns = [
        (lambda f, ck=ck: fab.fabric_restore(f, template, ck, rows=rows))
        for ck in cks
    ]
    fns.append(lambda f: fab.fabric_restore(f, template, None, rows=rows))
    full_results, full_s = run_world(fns)
    full_joiner_wire = full_results[-1].stats.bytes_received
    assert full_joiner_wire == total
    del full_results, cks

    # --- shard-only residency: ranks 0..W-2 hold exactly their wanted
    # shards, rank W-1 is an EMPTY joiner; nobody ever assembles a
    # full leaf (shard_restore pulls into per-shard buffers).
    residents = [fab.ShardReplicaStore(keep_steps=2) for _ in range(W)]
    for r in range(W - 1):
        for i in layout.wanted(r):
            s = layout.shards[i]
            data = np.frombuffer(
                fab.byte_view(leaves[s.leaf])[
                    s.offset : s.offset + s.length
                ],
                np.uint8,
            ).copy()
            residents[r].put(
                10, s.leaf, s.offset, s.length, data, zlib.crc32(data)
            )

    def member(r):
        return lambda f: fab.shard_restore(
            f,
            template,
            residents[r],
            rows=rows,
            k=K,
            shard_bytes=shard_b,
        )

    results, shard_s = run_world([member(r) for r in range(W)])
    joiner = results[-1]
    assert joiner.stats.mode == "fabric"
    joiner_wire = joiner.stats.bytes_received

    # Peak host checkpoint bytes per member: measured resident bytes
    # + one in-flight shard buffer (the pull lands per shard).
    peak_member = max(residents[r].nbytes() for r in range(W)) + shard_b
    bit_identical = all(
        bytes(residents[r].get(10, s.leaf, s.offset, s.length))
        == bytes(
            fab.byte_view(leaves[s.leaf])[s.offset : s.offset + s.length]
        )
        for r in range(W)
        for s in (layout.shards[i] for i in layout.wanted(r))
    )
    covered = set()
    for r in range(W):
        covered.update(layout.wanted(r))

    # --- replication ack (the K-ring durability figure, ISSUE 20
    # satellite closing PR 19's residue): rank 0 offers its owned
    # shards to every ring buddy over real FabricServers and the
    # replication round ACKS — replicate_to_buddies returns with
    # underreplicated == 0, meaning each owned shard reached all K
    # buddies.  The wall time of that round is the ack latency a
    # collective flush's stage-B hook pays before step 10 counts as
    # K-replicated.
    ck = HostCheckpoint(
        step=10, generation=1, leaves=list(leaves), treedef=treedef
    )
    digs = ck.shard_digests(layout)
    buddy_reps = {r: fab.ShardReplicaStore() for r in range(1, W)}
    buddy_srvs = {
        r: fab.FabricServer(
            lambda *a: None,
            ingest=fab.ReplicaIngest(
                buddy_reps[r], lambda *a: False
            ),
        ).start()
        for r in range(1, W)
    }
    try:
        peer_addrs = {
            r: ("127.0.0.1", buddy_srvs[r].port) for r in range(1, W)
        }

        def shard_source(s):
            view = fab.byte_view(leaves[s.leaf])
            return view[s.offset : s.offset + s.length], digs[s.index]

        t0 = time.perf_counter()
        rep_summary = fab.replicate_to_buddies(
            layout, 0, 10, 1, peer_addrs, shard_source
        )
        replicate_ack_s = time.perf_counter() - t0
    finally:
        for srv in buddy_srvs.values():
            srv.stop()
    replication = {
        "k": K,
        "offered": rep_summary["offered"],
        "accepted": rep_summary["accepted"],
        "bytes_mb": round(rep_summary["bytes"] / 1e6, 1),
        "dropped": rep_summary["dropped"],
        "underreplicated": rep_summary["underreplicated"],
        "replicate_ack_ms": round(replicate_ack_s * 1000.0, 1),
    }

    print(
        json.dumps(
            {
                "world": W,
                "k": K,
                "state_mb": round(total / 1e6, 1),
                "shard_mb": shard_b >> 20,
                # the gated memory claim: shard-only peak vs the
                # full-copy residency where member bytes == state
                "peak_member_mb": round(peak_member / 1e6, 1),
                "full_copy_member_mb": round(total / 1e6, 1),
                "peak_member_bytes_ratio": round(peak_member / total, 4),
                "joiner_wire_mb": round(joiner_wire / 1e6, 1),
                "full_copy_joiner_wire_mb": round(
                    full_joiner_wire / 1e6, 1
                ),
                "joiner_wire_ratio": round(joiner_wire / total, 4),
                "bit_identical": bool(bit_identical),
                "union_covers_all_shards": covered
                == set(range(len(layout.shards))),
                "replication": replication,
                "shard_only_restore_s": round(shard_s, 4),
                "full_copy_restore_s": round(full_s, 4),
            }
        )
    )


if __name__ == "__main__":
    if "--restore-child" in sys.argv:
        i = sys.argv.index("--restore-child")
        _restore_child(int(sys.argv[i + 1]), int(sys.argv[i + 2]))
    elif "--fabric-sweep-child" in sys.argv:
        i = sys.argv.index("--fabric-sweep-child")
        _fabric_sweep_child(sys.argv[i + 1])
    elif "--shard-only-child" in sys.argv:
        i = sys.argv.index("--shard-only-child")
        _shard_only_child(sys.argv[i + 1])
