"""bench resize — the headline elastic-resize-latency sections.

ROADMAP item 5's per-module split, final tranche: the single-process
resize cycle (``bench_resize``, the round record's headline metric)
and the true cross-size CPU-mesh variant (``bench_cpu_cross_size``)
move here from the monolithic ``bench.py``.  ``bench.py`` stays the
driver that composes sections into the ONE JSON round record.
"""

from __future__ import annotations

import json
import statistics
import sys

RESIZE_BUDGET_S = 60.0


def bench_resize(model_name: str = "mnist", steps_per_phase: int = 10) -> dict:
    import jax
    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer

    devices = jax.devices()
    n_dev = len(devices)
    sizes = sorted({1, max(1, n_dev // 2), n_dev})

    model = get_model(model_name)
    data = ShardedDataIterator(
        synthetic_dataset(model.synth_batch, 4096),
        global_batch_size=max(64, 8 * n_dev),
    )
    coord = LocalCoordinator(target_world=1, max_world=n_dev)
    for i in range(n_dev):
        coord.register(f"t{i}")
    et = ElasticTrainer(
        model,
        optax.sgd(0.05),
        data,
        coord,
        devices=devices,
        # Coprime with steps_per_phase: resizes then land BETWEEN
        # interval saves, so the measured flush is the real split flush
        # (ordered d2h + overlapped hash/spill, with flush_bg phases
        # published) — a divisible interval would dedupe every resize
        # flush against the just-landed interval save and hide it.
        checkpoint_interval=7,
    )
    # Warm the compiled-step executables for every size (abstract AOT —
    # zero device allocation) so the measured window is the true warm
    # resize path, not first-compile; production gets the same warmth
    # from the autoscaler prewarm hint + persistent compile cache.
    et.precompile(sizes)
    # The warm run must cross ONE interval save: the save path's d2h
    # snapshot-copy jits compile on their first dispatch, and without a
    # pre-cycle save the first resize's flush would pay them inside the
    # measured window (they are steady-state cost, not resize cost).
    target = max(steps_per_phase, et.checkpoint_interval + 1)
    et.run(target)

    # Count TRUE XLA compiles per resize window at the backend_compile
    # seam (persistent-cache hits bypass it): the acceptance bar is
    # ZERO inside a warm resize, and a nonzero count here names the
    # exact cycle that regressed.  The count lives in the SHARED
    # telemetry registry (edl_xla_compiles_total) — bench reads the
    # same exposition surface production scrapes, instead of the
    # private list it used to keep.
    import jax._src.compiler as _compiler

    from edl_tpu import telemetry

    m_compiles = telemetry.get_registry().counter("edl_xla_compiles_total")
    _real_bc = _compiler.backend_compile

    def _counting_bc(*args, **kwargs):
        m_compiles.inc()
        return _real_bc(*args, **kwargs)

    resize_windows = []
    step_times = []
    resize_events = []
    # Per-phase samples (flush / remesh / restore / first_step) so a
    # headline regression is attributable to ONE phase (the r4->r5
    # resize_max 0.33->0.80s jump was not).
    phase_samples: dict = {}
    # Cycle up then down through world sizes (e.g. 1 -> 4 -> 8 -> 4 -> 1).
    # On a single chip every entry is 1: the resize is then forced via
    # membership churn (leave+rejoin), which runs the identical barrier.
    cycle = (sizes[1:] + sizes[:-1][::-1]) or [1, 1, 1]
    prev_w = sizes[0]
    _compiler.backend_compile = _counting_bc
    try:
        for w in cycle:
            if w == prev_w:
                coord.deregister(f"t{w - 1}")
                coord.register(f"t{w - 1}")
            else:
                coord.set_target_world(w)
            prev_w = w
            compiles_before = m_compiles.value()
            first_step_marks: dict = {}

            def on_step(rec, marks=first_step_marks):
                # compile counter right after the FIRST step of each
                # generation: (mark - before) bounds the whole
                # resize-window-plus-first-step compile count, before
                # any later interval save's copy jits muddy it.
                if rec.generation not in marks:
                    marks[rec.generation] = m_compiles.value()

            et.maybe_resize()
            target += steps_per_phase
            et.run(target, on_step=on_step)
            gen = et.generation
            first = next(r for r in et.history if r.generation == gen)
            # Window = resize barrier (event.seconds) + first post-resize
            # step.
            event = et.resize_events[-1]
            assert event.generation == gen
            resize_windows.append(event.seconds + first.seconds)
            for name, secs in (event.phase_seconds or {}).items():
                phase_samples.setdefault(name, []).append(secs)
            phase_samples.setdefault("first_step", []).append(first.seconds)
            step_times.extend(r.seconds for r in et.history[-3:])
            resize_events.append(
                {
                    "world_size": event.world_size,
                    "graceful": event.graceful,
                    "seconds": round(event.seconds, 4),
                    "first_step_s": round(first.seconds, 4),
                    "xla_compiles": int(
                        first_step_marks.get(gen, m_compiles.value())
                        - compiles_before
                    ),
                    "phase_seconds": event.phase_seconds,
                }
            )
    finally:
        _compiler.backend_compile = _real_bc

    # Join any in-flight async checkpoint thread before teardown (a live
    # device->host copy racing interpreter exit aborts the TPU runtime).
    et.store.wait()

    # Steady-state telemetry overhead: time the EXACT per-step ops the
    # elastic loop performs (recorder context stamp + steps counter inc
    # + step-seconds histogram observe) on a scoped throwaway registry,
    # and express the per-step cost against this run's median step time
    # — the default-on registry's acceptance bar is < 1%.
    import time

    median_step = statistics.median(step_times)
    with telemetry.scoped() as (treg, trec):
        tc = treg.counter("edl_steps_total")
        th = treg.histogram("edl_step_seconds")
        n_ops = 20000
        t0 = time.perf_counter()
        for i in range(n_ops):
            trec.set_context(i, 0)
            tc.inc()
            th.observe(0.001)
        per_step_overhead = (time.perf_counter() - t0) / n_ops

    # Goodput ledger across the whole cycle (steady stepping + every
    # resize + any replay), read from the same shared registry a
    # production scrape sees: the fraction of wall clock spent
    # stepping, with the resizing[:phase] / holding / replaying
    # decomposition the autoscaler's decision log records.
    from edl_tpu.telemetry import goodput_decomposition

    goodput = goodput_decomposition(telemetry.get_registry().snapshot())

    return {
        "telemetry": {
            "per_step_overhead_s": round(per_step_overhead, 9),
            "median_step_s": round(median_step, 6),
            "overhead_frac": round(per_step_overhead / median_step, 6),
            # read back from the SHARED registry (what /metrics serves)
            "steps_total": et._m_steps.value(),
        },
        "goodput": goodput,
        "goodput_frac": (goodput or {}).get("frac"),
        "resize_s": statistics.median(resize_windows),
        "resize_max_s": max(resize_windows),
        "step_s": statistics.median(step_times),
        "n_devices": n_dev,
        "world_cycle": cycle,
        "resize_phases": {
            name: {
                "median_s": round(statistics.median(xs), 4),
                "max_s": round(max(xs), 4),
            }
            for name, xs in sorted(phase_samples.items())
        },
        # Per-resize attribution (the r5 honesty fix): every resize's
        # full phase breakdown + its true-compile count, published into
        # the round record so the NEXT regression is attributable to
        # one phase of one cycle instead of a single opaque max.
        "resize_events": resize_events,
        "warm_resize_xla_compiles": max(
            (ev["xla_compiles"] for ev in resize_events), default=0
        ),
    }


def bench_cpu_cross_size(n_devices: int = 8) -> dict:
    """True cross-size resize (1 -> n/2 -> n -> n/2 -> 1) measured on a
    forced ``n_devices`` virtual-CPU mesh in a hermetic subprocess.

    The single-chip headline above can only exercise the leave/rejoin
    barrier (world stays 1); this figure tracks the real re-mesh +
    resharding-restore path the <60s BASELINE.md budget is about.
    """
    from edl_tpu.utils.hermetic import virtual_cpu_env

    from bench_lib.lm import run_bench_child

    return run_bench_child(
        "--cross-size-child",
        module="bench_lib.resize",
        env=virtual_cpu_env(n_devices),
    )


def _cross_size_child():
    """Child entry: measure bench_resize on the forced-CPU mesh and print
    its raw dict as JSON (consumed by bench_cpu_cross_size)."""
    from edl_tpu.utils.hermetic import pin_cpu_platform

    pin_cpu_platform()
    r = bench_resize(steps_per_phase=5)
    print(json.dumps(r))


if __name__ == "__main__":
    if "--cross-size-child" in sys.argv:
        _cross_size_child()
