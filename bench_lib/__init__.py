"""bench_lib — per-section benchmark modules.

ROADMAP item 5's split of the monolithic ``bench.py``: each bench
section that outgrows a screenful moves into its own module here, and
``bench.py`` stays the driver that composes sections into the ONE JSON
round record.  Sections land here as they grow — serving and the fleet
storm first (this round), the remaining sections as they next change.

Shared harness pieces (the open-loop load generator) live here too so
every "heavy traffic" claim in the record is measured the same way.
"""
